"""Mixed-precision PCG solve mode of the dense backend.

The mode replaces the f64 direct factorization with an f32-Cholesky
preconditioner + matrix-free CG whose operator applies A·diag(d)·Aᵀ in
the iterate dtype (backends/dense.py:_pcg_ops). It exists for
reference-scale dense problems (BASELINE.json:9) where emulated-f64
assembly/Cholesky is intractable; these tests pin its algebra on CPU
(where f64 is native) — full-tolerance agreement with HiGHS through the
single-phase, two-phase, and segmented execution paths.
"""

import jax
import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import Status
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.models.problem import to_interior_form

from tests.oracle import highs_on_general


def _check_optimal(r, p):
    assert r.status == Status.OPTIMAL
    assert r.rel_gap <= 1e-8 and r.pinf <= 1e-8 and r.dinf <= 1e-8
    ref = highs_on_general(p)
    np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)


def test_pcg_single_phase_full_tol():
    p = random_dense_lp(60, 180, seed=0)
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    be = DenseJaxBackend()
    r = solve(p, backend=be, solve_mode="pcg")
    assert be._pcg and not be._two_phase  # CPU platform: no phase schedule
    _check_optimal(r, p)


def test_pcg_as_phase2_of_two_phase(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    p = random_dense_lp(40, 100, seed=1)
    be = DenseJaxBackend()
    r = solve(p, backend=be, solve_mode="pcg", use_pallas=False)
    assert be._pcg and be._two_phase
    _check_optimal(r, p)


def test_pcg_segmented(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    p = random_dense_lp(40, 100, seed=2)
    r = solve(p, backend="tpu", solve_mode="pcg", use_pallas=False,
              segment_iters=2)
    _check_optimal(r, p)


def test_pcg_auto_resolution():
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    inf = to_interior_form(random_dense_lp(20, 50, seed=3))
    be = DenseJaxBackend()
    be.setup(inf, SolverConfig())
    assert not be._pcg  # auto: small problem / CPU platform


def test_pcg_sharded_on_mesh():
    # PCG under GSPMD: the chunked matrix-free operator and the
    # replicated f32 preconditioner compile over the mesh; dropping the
    # f64 factorization halves the replicated per-device footprint
    # (VERDICT.md round 1 item 8, first cut).
    from distributedlpsolver_tpu.backends.sharded import ShardedJaxBackend
    from distributedlpsolver_tpu.parallel import make_mesh

    p = random_dense_lp(48, 128, seed=4)
    be = ShardedJaxBackend(mesh=make_mesh(devices=jax.devices()[:8]))
    r = solve(p, backend=be, solve_mode="pcg")
    assert be._pcg
    _check_optimal(r, p)


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="memory-crossover claim is TPU-specific: CPU XLA's buffer "
    "assignment fuses the direct-f64 Cholesky differently (and f64 is "
    "native there), so temp_size_in_bytes does not reproduce the "
    "documented ordering off-TPU",
)
def test_pcg_memory_analysis_beats_direct_f64():
    # Compile-time per-device memory of one full-accuracy step at a
    # mid-size shape: the PCG step (f32 preconditioner + matrix-free CG)
    # must allocate less temp memory than the direct-f64 step it replaces
    # (which materializes M and its Cholesky factor in f64). This is the
    # documented memory crossover for the replicated-factorization relief.
    import jax.numpy as jnp
    from distributedlpsolver_tpu.backends import dense as D
    from distributedlpsolver_tpu.ipm.config import SolverConfig as SC

    m, n = 512, 1536
    inf = to_interior_form(random_dense_lp(m, n, seed=5))
    A = jnp.asarray(np.asarray(inf.A), dtype=jnp.float64)
    from distributedlpsolver_tpu.ipm import core as C

    data = C.make_problem_data(
        jnp, jnp.asarray(inf.c), jnp.asarray(inf.b), jnp.asarray(inf.u),
        jnp.float64,
    )
    params = SC().step_params()

    from distributedlpsolver_tpu.ipm.state import IPMState

    key_state = IPMState(
        x=jnp.ones(inf.n), y=jnp.zeros(inf.m), s=jnp.ones(inf.n),
        w=jnp.ones(inf.n), z=jnp.zeros(inf.n),
    )
    reg = jnp.asarray(1e-10, jnp.float64)

    def mem(fn, *args, **kw):
        lowered = jax.jit(
            fn, static_argnames=tuple(kw.keys())
        ).lower(*args, **kw)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    def direct_step(A, data, state, reg):
        ops = D._make_ops(A, reg, jnp.dtype(jnp.float64), 0, False, None)
        return C.mehrotra_step(ops, data, params, state)

    A32 = A.astype(jnp.float32)

    def pcg_step(A, A32, data, state, reg):
        ops = D._make_ops(
            A, reg, jnp.dtype(jnp.float32), 0, False, A32, 100, 1e-11
        )
        return C.mehrotra_step(ops, data, params, state)

    m_direct = mem(direct_step, A, data, key_state, reg)
    m_pcg = mem(pcg_step, A, A32, data, key_state, reg)
    assert m_pcg < m_direct, (m_pcg, m_direct)


def test_pcg_host_driver_path():
    # fused_loop=False exercises starting_point + per-iteration iterate()
    # through the PCG ops.
    p = random_dense_lp(30, 90, seed=5)
    r = solve(p, backend="tpu", solve_mode="pcg", fused_loop=False)
    _check_optimal(r, p)


class TestBlockPCG:
    """PCG mode of the block-angular Schur backend (same design, arrow
    structure: f32 block/linking factorization preconditioner +
    full-precision matrix-free CG through the block tensors)."""

    def test_block_pcg_matches_highs(self):
        from distributedlpsolver_tpu.models.generators import block_angular_lp
        from distributedlpsolver_tpu.backends.block_angular import (
            BlockAngularBackend,
        )

        p = block_angular_lp(6, 24, 48, 12, seed=3, sparse=False)
        be = BlockAngularBackend()
        r = solve(p, backend=be, solve_mode="pcg", scale=False)
        assert be._pcg
        assert r.status == Status.OPTIMAL
        assert r.rel_gap <= 1e-8
        ref = highs_on_general(p)
        np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)

    def test_block_pcg_segmented(self, monkeypatch):
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        from distributedlpsolver_tpu.models.generators import block_angular_lp

        p = block_angular_lp(4, 16, 32, 8, seed=4, sparse=False)
        r = solve(p, backend="block", solve_mode="pcg", scale=False,
                  segment_iters=2)
        assert r.status == Status.OPTIMAL
        ref = highs_on_general(p)
        np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)

    def test_block_pcg_on_mesh(self):
        # The arrow-structure PCG is pure einsum + vector work, so it
        # shards over the K axis like the direct path.
        from distributedlpsolver_tpu.models.generators import block_angular_lp
        from distributedlpsolver_tpu.backends.block_angular import (
            BlockAngularBackend,
        )
        from distributedlpsolver_tpu.parallel import make_mesh

        p = block_angular_lp(8, 12, 24, 8, seed=5, sparse=False)
        mesh = make_mesh(devices=jax.devices()[:8])
        r = solve(p, backend=BlockAngularBackend(mesh=mesh),
                  solve_mode="pcg", scale=False)
        assert r.status == Status.OPTIMAL
        ref = highs_on_general(p)
        np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)

    def test_block_pcg_host_driver(self):
        from distributedlpsolver_tpu.models.generators import block_angular_lp

        p = block_angular_lp(4, 16, 32, 8, seed=6, sparse=False)
        r = solve(p, backend="block", solve_mode="pcg", scale=False,
                  fused_loop=False)
        assert r.status == Status.OPTIMAL
        ref = highs_on_general(p)
        np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)


def _force_endgame(monkeypatch, **extra):
    """Run a small PCG solve that GENUINELY enters the endgame loop.

    On a well-conditioned toy the f32 preconditioner is essentially
    exact, so the PCG phase cannot be made to floor the way it does at
    reference scale (observed there: hard pinf floor ~3e-7). Instead the
    fused phases' iteration budget is truncated at the host driver so
    they exit MAXITER with a genuinely unconverged iterate — the endgame
    must then do real full-precision work to reach 1e-8. Returns
    (backend, result, problem)."""
    import distributedlpsolver_tpu.backends.dense as d

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(d.DenseJaxBackend, "_ENDGAME_ENTRIES", 1)
    real_dpp = d.core.drive_phase_plan

    def truncated(phases, state, reg0, max_iter, buf_cap, dtype, **kw):
        return real_dpp(phases, state, reg0, 4, buf_cap, dtype, **kw)

    monkeypatch.setattr(d.core, "drive_phase_plan", truncated)
    p = random_dense_lp(48, 128, seed=6)
    be = d.DenseJaxBackend()
    r = solve(p, backend=be, solve_mode="pcg", use_pallas=False, **extra)
    return be, r, p


def test_endgame_finishes_after_pcg_floor(monkeypatch):
    # Phase 1 f32 -> phase 2 PCG (crippled: stalls below tol) ->
    # host-driven endgame iterations with the factorization computed in
    # separate dispatches. Must reach full 1e-8 optimality, and must
    # actually have run the endgame (per-dispatch timings recorded).
    be, r, p = _force_endgame(monkeypatch)
    assert be._pcg
    _check_optimal(r, p)
    # the history must be contiguous through the endgame append
    assert len(r.history) == r.iterations
    tm = [row for row in be.endgame_timings if "t_step" in row]
    assert tm, "endgame loop was never entered"
    assert {"it", "t_assemble", "t_factor", "t_step", "bad", "reg"} <= set(
        tm[0]
    )
    # the endgame is a phase_report row too — without it the utilization
    # artifacts under-attribute exactly the endgame iterations
    rep = be.phase_report
    assert rep[-1]["mode"] == "endgame"
    assert sum(ph["iters"] for ph in rep) == r.iterations
    # seeded reg is capped: f32-phase escalations must not pin the f64
    # finish above tol (code-review finding, round 3)
    assert all(row["reg"] <= 1e-6 + 1e-18 for row in tm if not row["bad"])


def test_endgame_bad_step_escalates_without_reassembly(monkeypatch):
    # A bad step must re-run ONLY factor+step with escalated reg — the
    # assembly (longest dispatch at scale) is reused for the same iterate.
    # Pinned to the DEVICE factor path (endgame_host=False): the forced
    # badness is injected into the device step function.
    import distributedlpsolver_tpu.backends.dense as d

    real_step = d._endgame_step
    real_asm = d._endgame_assemble
    forced = {"n": 0}
    asm_calls = {"n": 0}

    def bad_once_step(A, data, state, L, reg, diagM, params, refine=1):
        new_state, stats = real_step(A, data, state, L, reg, diagM, params,
                                     refine=refine)
        if forced["n"] == 0:
            forced["n"] += 1
            stats = stats._replace(bad=True)
        return new_state, stats

    def counting_asm(A, data, state, params):
        asm_calls["n"] += 1
        return real_asm(A, data, state, params)

    monkeypatch.setattr(d, "_endgame_step", bad_once_step)
    monkeypatch.setattr(d, "_endgame_assemble", counting_asm)
    be, r, p = _force_endgame(monkeypatch, endgame_host=False)
    _check_optimal(r, p)
    tm = be.endgame_timings
    bad_rows = [row for row in tm if row["bad"]]
    assert len(bad_rows) == 1  # the forced one
    # retry escalated reg relative to the failed attempt...
    i = tm.index(bad_rows[0])
    assert tm[i + 1]["reg"] > bad_rows[0]["reg"]
    # ...WITHOUT a fresh assembly: one assemble per endgame ITERATE, not
    # per attempt (attempts == len(tm) > iterates when a retry happened)
    assert asm_calls["n"] == len(tm) - len(bad_rows)
    # and the retry row records no assembly time of its own
    assert tm[i + 1]["t_assemble"] == 0.0


def test_endgame_numerical_error_exit(monkeypatch):
    # Persistent bad steps must escalate reg to the cap and exit
    # NUMERICAL_ERROR instead of looping forever.
    import distributedlpsolver_tpu.backends.dense as d

    real_step = d._endgame_step

    def always_bad(A, data, state, L, reg, diagM, params, refine=1):
        new_state, stats = real_step(A, data, state, L, reg, diagM, params,
                                     refine=refine)
        return new_state, stats._replace(bad=True)

    monkeypatch.setattr(d, "_endgame_step", always_bad)
    be, r, p = _force_endgame(monkeypatch, endgame_host=False)
    assert r.status == Status.NUMERICAL_ERROR
    tm = be.endgame_timings
    assert all(row["bad"] for row in tm)
    regs = [row["reg"] for row in tm]
    assert regs == sorted(regs) and regs[-1] > regs[0]  # monotone escalation


def test_endgame_stall_exit(monkeypatch):
    # Steps that stop improving must trip the endgame's stall window and
    # exit STALLED rather than burning the whole iteration budget.
    import distributedlpsolver_tpu.backends.dense as d

    real_step = d._endgame_step

    def frozen_step(A, data, state, L, reg, diagM, params, refine=1):
        _, stats = real_step(A, data, state, L, reg, diagM, params,
                             refine=refine)
        return state, stats  # no progress: same iterate every time

    monkeypatch.setattr(d, "_endgame_step", frozen_step)
    be, r, p = _force_endgame(monkeypatch, endgame_host=False,
                              stall_window=3, max_iter=60)
    assert r.status == Status.STALLED
    # it gave up well before the iteration budget
    assert len(be.endgame_timings) < 40


def test_pcg_sharded_preconditioner_memory_and_agreement():
    """The column-sharded L⁻¹ build (dense._tri_inv_mesh) must (a) agree
    with the replicated build and (b) cut per-device compiled memory of a
    PCG step on the mesh — the distributed-factorization first cut
    (VERDICT round 2 item 5: 'per-device peak memory measurably below
    the replicated-PCG baseline')."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedlpsolver_tpu.backends import dense as D
    from distributedlpsolver_tpu.ipm import core as C
    from distributedlpsolver_tpu.ipm.config import SolverConfig as SC
    from distributedlpsolver_tpu.ipm.state import IPMState
    from distributedlpsolver_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh((8,), axis_names=("cols",))
    psh = NamedSharding(mesh, P(None, "cols"))

    # (a) numerical agreement of the sharded triangular inverse
    rng = np.random.default_rng(3)
    m = 96
    Lt = np.tril(rng.standard_normal((m, m))) + 4.0 * np.eye(m)
    L = jnp.asarray(Lt, dtype=jnp.float32)
    ref = np.asarray(D._tri_inv_paneled(L, panel=32))
    got = np.asarray(jax.jit(lambda L: D._tri_inv_mesh(L, psh, panel=8))(L))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    # (b) per-device compiled memory: sharded factor beats replicated
    mm, nn = 512, 1024
    inf = to_interior_form(random_dense_lp(mm, nn, seed=5))
    A = jax.device_put(
        jnp.asarray(np.asarray(inf.A), dtype=jnp.float64),
        NamedSharding(mesh, P(None, "cols")),
    )
    A32 = A.astype(jnp.float32)
    data = C.make_problem_data(
        jnp, jnp.asarray(inf.c), jnp.asarray(inf.b), jnp.asarray(inf.u),
        jnp.float64,
    )
    params = SC().step_params()
    key_state = IPMState(
        x=jnp.ones(inf.n), y=jnp.zeros(inf.m), s=jnp.ones(inf.n),
        w=jnp.ones(inf.n), z=jnp.zeros(inf.n),
    )
    reg = jnp.asarray(1e-10, jnp.float64)

    def mem(prec_shard):
        def step(A, A32, data, state, reg):
            ops = D._make_ops(
                A, reg, jnp.dtype(jnp.float32), 0, False, A32, 100, 1e-11,
                prec_shard,
            )
            return C.mehrotra_step(ops, data, params, state)

        lowered = jax.jit(step).lower(A, A32, data, key_state, reg)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    m_repl = mem(None)
    m_shard = mem(psh)
    # The replicated step holds the full m×m f64 L⁻¹ per device; the
    # sharded step holds m×(m/8). Demand a real margin (≥ 2·m² bytes —
    # a quarter of the f64 factor), not noise: buffer reuse means the
    # full 7/8·8m² savings is not visible in temp accounting.
    assert m_shard < m_repl - 2 * mm * mm, (m_shard, m_repl)

    # (c) end-to-end on the mesh through the public API
    from distributedlpsolver_tpu.backends.sharded import ShardedJaxBackend

    p = random_dense_lp(64, 160, seed=9)
    be = ShardedJaxBackend(mesh=mesh)
    r = solve(p, backend=be, solve_mode="pcg")
    assert be._prec_shard is not None
    _check_optimal(r, p)


class TestHostEndgame:
    """Host-LAPACK endgame factorization + feasibility projection
    (cfg.endgame_host; auto = on under emulated f64). These are the two
    mechanisms that broke the round-3 10k×50k terminal wall — the
    emulated-f64 Cholesky NaN floor and the reg-filtered pinf floor
    (BENCH_10K.json analysis) — pinned here at toy scale on CPU."""

    def test_auto_endgame_is_mxu_and_finishes(self, monkeypatch):
        # auto-resolution: endgame_host=None on (monkeypatched) TPU ->
        # the on-device mxu mode (round 5). Must reach 1e-8 with mxu
        # step rows in the timing record; the pure-jax AAᵀ closure keeps
        # the final iterate essentially on Ax=b.
        be, r, p = _force_endgame(monkeypatch)
        _check_optimal(r, p)
        tm = be.endgame_timings
        assert all(row.get("mode") == "mxu" for row in tm)
        assert not any(row.get("host") for row in tm)
        assert r.pinf < 1e-10

    def test_host_endgame_finishes(self, monkeypatch):
        # Explicit endgame_host=True keeps the LAPACK escape hatch: host
        # step rows (with a transfer phase) in the timing record, same
        # 1e-8 finish, pinf pinned by the host AAᵀ closure.
        be, r, p = _force_endgame(monkeypatch, endgame_host=True)
        _check_optimal(r, p)
        tm = be.endgame_timings
        assert any(row.get("host") for row in tm)
        steps = [row for row in tm if "t_step" in row and not row["bad"]]
        assert steps and all("t_transfer" in row for row in steps)
        assert r.pinf < 1e-10

    def test_host_factor_failure_escalates_without_retransfer(
        self, monkeypatch
    ):
        # A host factorization failure must walk the reg ladder on the
        # HELD host copy: no step dispatch, no re-assembly, no re-transfer
        # for the retry; the eventual good step runs at the escalated reg.
        import distributedlpsolver_tpu.backends.dense as d

        real_fac = d._endgame_factor_host
        # call 0 is the projector build (same helper) — let it succeed,
        # then fail the endgame loop's first two factorizations
        calls = {"n": 0}

        def flaky(Mh, reg):
            calls["n"] += 1
            if calls["n"] in (2, 3):
                return None
            return real_fac(Mh, reg)

        monkeypatch.setattr(d, "_endgame_factor_host", flaky)
        be, r, p = _force_endgame(monkeypatch, endgame_host=True)
        _check_optimal(r, p)
        tm = [row for row in be.endgame_timings if "t_step" in row]
        assert [row["bad"] for row in tm[:3]] == [True, True, False]
        assert tm[0]["L_finite"] is False and tm[1]["L_finite"] is False
        # ladder retries paid neither assembly nor transfer again
        assert tm[1]["t_assemble"] == 0.0 and tm[1]["t_transfer"] == 0.0
        assert tm[2]["t_assemble"] == 0.0 and tm[2]["t_transfer"] == 0.0
        assert tm[2]["reg"] > tm[0]["reg"]

    def test_host_bad_step_retries_from_held_copy(self, monkeypatch):
        # A bad STEP (finite factor, zero step) in host mode must retry
        # with escalated reg from the held host M — no re-assembly.
        import distributedlpsolver_tpu.backends.dense as d

        real_step = d._endgame_step_host
        real_asm = d._endgame_assemble
        forced = {"n": 0}
        asm_calls = {"n": 0}

        def bad_once(A, data, state, hostf, reg, diagM, params, refine=1,
                     restore=None):
            new_state, stats = real_step(
                A, data, state, hostf, reg, diagM, params, refine=refine,
                restore=restore,
            )
            if forced["n"] == 0:
                forced["n"] += 1
                stats = stats._replace(bad=True)
            return new_state, stats

        def counting_asm(A, data, state, params):
            asm_calls["n"] += 1
            return real_asm(A, data, state, params)

        monkeypatch.setattr(d, "_endgame_step_host", bad_once)
        monkeypatch.setattr(d, "_endgame_assemble", counting_asm)
        be, r, p = _force_endgame(monkeypatch, endgame_host=True)
        _check_optimal(r, p)
        tm = [row for row in be.endgame_timings if "t_step" in row]
        bad_rows = [row for row in tm if row["bad"]]
        assert len(bad_rows) == 1
        i = tm.index(bad_rows[0])
        assert tm[i + 1]["reg"] > bad_rows[0]["reg"]
        assert tm[i + 1]["t_assemble"] == 0.0
        assert tm[i + 1]["t_transfer"] == 0.0
        assert asm_calls["n"] == len(tm) - len(bad_rows)


@pytest.mark.parametrize("m", [1, 5, 97, 256, 1000, 1023])
def test_fetch_symmetric_exact(m):
    """The lower-triangle d2h fetch (dense._fetch_symmetric) must
    reconstruct a symmetric matrix EXACTLY (bitwise) — the host endgame
    factors what it returns, so any mirroring defect becomes a silent
    factorization of the wrong matrix."""
    import jax.numpy as jnp

    import distributedlpsolver_tpu.backends.dense as d

    rng = np.random.default_rng(m)
    G = rng.standard_normal((m, m))
    S = G + G.T
    got = d._fetch_symmetric(jnp.asarray(S))
    assert got.dtype == np.float64
    np.testing.assert_array_equal(got, S)


def test_pure_centering_step_improves_centrality():
    """StepParams.center: a pure centering step on a badly off-center
    iterate must raise the worst product/μ ratio while staying feasible —
    the blocked-step remedy the endgame's anti-stagnation ladder fires."""
    import dataclasses

    import jax.numpy as jnp

    import distributedlpsolver_tpu.backends.dense as d
    from distributedlpsolver_tpu.ipm import core as C
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.ipm.state import IPMState

    rng = np.random.default_rng(4)
    m, n = 12, 32
    A = jnp.asarray(rng.standard_normal((m, n)))
    x0 = jnp.asarray(rng.uniform(0.5, 2.0, n))
    b = A @ x0
    data = C.make_problem_data(
        jnp, jnp.asarray(rng.standard_normal(n)), b,
        jnp.full(n, jnp.inf), jnp.float64,
    )
    # off-center: a handful of products orders below the average
    s0 = jnp.asarray(rng.uniform(0.5, 2.0, n)).at[:4].set(1e-6)
    st = IPMState(x=x0, y=jnp.zeros(m), s=s0, w=jnp.ones(n),
                  z=jnp.zeros(n))
    params = dataclasses.replace(SolverConfig().step_params(), center=True)
    ops = d._make_ops(A, jnp.asarray(1e-10), jnp.dtype(jnp.float64), 0,
                      False, None, 0, 0.0, None)
    r0, _, _ = d._cent_diag(data, st, jnp.asarray(params.gamma_cent))
    st1, stats = C.mehrotra_step(ops, data, params, st)
    r1, _, _ = d._cent_diag(data, st1, jnp.asarray(params.gamma_cent))
    assert not bool(np.asarray(stats.bad))
    assert float(np.asarray(stats.sigma)) == 1.0
    # centrality must improve by a real factor, not noise
    assert float(np.asarray(r1)) > 10 * float(np.asarray(r0))
    assert np.all(np.asarray(st1.x) > 0) and np.all(np.asarray(st1.s) > 0)


def test_endgame_stagnation_fires_centering_ladder(monkeypatch):
    """μ-stagnant accepted steps must trigger the anti-stagnation ladder:
    a pure centering step after ONE sub-10%-μ step (round-5 one-strike
    trigger; center=True param reaching the step, row flagged), the
    collapsed-pair lift after three consecutive strikes, and the run
    still finishing OPTIMAL once the (simulated) blockage lifts."""
    import distributedlpsolver_tpu.backends.dense as d

    real_step = d._endgame_step_host
    real_recenter = d._endgame_recenter
    sim = {"blocked": 0, "centers": 0, "recenters": 0}

    def blocked_then_real(A, data, state, hostf, reg, diagM, params,
                          refine=1, restore=None):
        import jax.numpy as jnp

        new_state, stats = real_step(
            A, data, state, hostf, reg, diagM, params, refine=refine,
            restore=restore,
        )
        if params.center:
            sim["centers"] += 1
        if sim["centers"] >= 2:
            return new_state, stats  # blockage lifted — run real
        # Simulate the blocked-step mode: the iterate does not move and
        # μ reports a CONSTANT, so the loop's stagnation counter climbs
        # deterministically through the whole ladder (1 strike → center,
        # 3 strikes → recenter + center) before the real solve resumes.
        sim["blocked"] += 1
        return state, stats._replace(
            alpha_p=jnp.asarray(0.005), alpha_d=jnp.asarray(0.01),
            mu=stats.mu * 0 + 1e-6, bad=stats.bad & False,
        )

    def counting_recenter(data, state, params):
        sim["recenters"] += 1
        return real_recenter(data, state, params)

    monkeypatch.setattr(d, "_endgame_step_host", blocked_then_real)
    monkeypatch.setattr(d, "_endgame_recenter", counting_recenter)
    be, r, p = _force_endgame(monkeypatch, endgame_host=True)
    _check_optimal(r, p)
    tm = [row for row in be.endgame_timings if "t_step" in row]
    # the ladder fired at least one centering step, flagged in the rows
    assert sim["centers"] >= 1
    assert any(row["center"] for row in tm)
    # ONE-strike trigger pinned: with constant-μ blocked steps the first
    # CENTER row must land by the third step (blocked, strike → center).
    # The old two-strike scheme centers one step later and fails this.
    assert any(row["center"] for row in tm[:3]), [r["center"] for r in tm[:5]]
    # entry recenter always runs once; the ladder's mid-loop lift adds one
    assert sim["recenters"] >= 2
    # every row carries the blocked-step diagnostics
    assert all("cent_ratio" in row and "n_below" in row for row in tm)


def test_host_projector_restores_feasibility_and_respects_bounds():
    """Unit test of the alternating-projections (POCS) projector: an
    iterate pushed off Ax=b must come back to ~machine feasibility
    WITHOUT violating positivity or finite upper bounds."""
    import jax.numpy as jnp
    import distributedlpsolver_tpu.backends.dense as d
    from distributedlpsolver_tpu.ipm import core as C
    from distributedlpsolver_tpu.ipm.state import IPMState

    rng = np.random.default_rng(11)
    m, n = 24, 64
    A = jnp.asarray(rng.standard_normal((m, n)))
    # late-IPM-like ground truth: m "basic" O(1) columns, the rest
    # collapsed tiny; b is consistent with THIS point, and the iterate
    # is knocked a small distance off it (the endgame regime: small,
    # reg-filtered feasibility drift on an otherwise converged iterate)
    x = np.full(n, 1e-9)
    basic = rng.choice(n, size=m, replace=False)
    x[basic] = np.abs(rng.standard_normal(m)) + 0.5
    b = A @ jnp.asarray(x)
    u = np.full(n, np.inf)
    u[:8] = x[:8] + 1.5  # a few finite upper bounds
    data = C.make_problem_data(
        jnp, jnp.asarray(rng.standard_normal(n)), b, jnp.asarray(u),
        jnp.float64,
    )
    x = jnp.asarray(x)
    x_off = x + 1e-5 * jnp.asarray(rng.standard_normal(n))
    x_off = jnp.maximum(x_off, 1e-12)
    st = IPMState(
        x=x_off, y=jnp.zeros(m), s=jnp.ones(n),
        w=jnp.where(data.hub > 0, jnp.maximum(data.u_f - x_off, 1e-12), 1.0),
        z=jnp.where(data.hub > 0, 1.0, 0.0),
    )
    pinf0 = float(d._eg_pinf(A, data, st.x, st.w))
    project = d._build_host_projector(A, data)
    assert project is not None
    st2, p0, p1 = project(st, rounds=40)
    assert p0 == pytest.approx(pinf0)
    # alternating projections contract geometrically (measured ~1.9x per
    # round on this construction); 40 rounds must buy several orders
    assert p1 < 1e-3 * p0
    x2 = np.asarray(st2.x)
    assert (x2 > 0).all()
    hub = np.asarray(data.hub) > 0
    assert (x2[hub] < np.asarray(data.u_f)[hub]).all()
    # the box projection keeps columns STRICTLY interior at every round
    # (asserted by the (x2 > 0).all() above); columns the affine set
    # persistently wants at zero decay geometrically (0.1x per round) —
    # approaching their true nonbasic value — rather than oscillating
    nonbasic = np.setdiff1d(np.arange(n), basic)
    assert x2[nonbasic].max() < 1e-3  # none blew up to basic scale


def test_host_factor_reports_breakdown_as_none():
    """_endgame_factor_host must report breakdown (indefinite /
    non-factorable input) by returning None — the ladder's retry signal —
    through the REAL scipy path, not a monkeypatch."""
    import distributedlpsolver_tpu.backends.dense as d

    rng = np.random.default_rng(3)
    B = rng.standard_normal((16, 16))
    indefinite = B + B.T  # symmetric, eigenvalues of both signs
    assert d._endgame_factor_host(indefinite, 1e-12) is None
    spd = B @ B.T + 16 * np.eye(16)
    out = d._endgame_factor_host(spd, 1e-12)
    assert out is not None
    L, s = out
    assert np.isfinite(L).all() and np.isfinite(s).all()
    # round-trip: the factor solves the Jacobi-scaled regularized system
    rhs = rng.standard_normal(16)
    import scipy.linalg as sla

    x = s * sla.cho_solve((L, True), s * rhs)
    sc = 1.0 / np.sqrt(np.diagonal(spd))
    Ms = spd + 1e-12 * np.diag(1.0 / sc**2)
    np.testing.assert_allclose(Ms @ x, rhs, rtol=1e-9, atol=1e-9)


def test_endgame_host_config_rejects_strings():
    with pytest.raises(ValueError):
        SolverConfig(endgame_host="host")
