"""Stochastic scenario tier tests (ISSUE 12): ScenarioLP model layer,
the scenario-decomposed two-stage IPM vs the lowered oracle, two_stage
structure detection/routing, and the scenario serve semantics —
fair-share unit admission, delta-wave warm-cache amortization, journal
round-trip, and the K-mixed zero-warm-recompile acceptance run."""

import json
import time

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.ipm.driver import solve as ipm_solve
from distributedlpsolver_tpu.models.problem import LPProblem, to_interior_form
from distributedlpsolver_tpu.models.scenario import (
    ScenarioLP,
    scenario_delta_stream,
    scenario_k_bucket,
    two_stage_storm,
)

from tests.oracle import highs_on_general

pytestmark = pytest.mark.scenario


def _small_storm(K, seed=0):
    return two_stage_storm(
        K, block_m=6, block_n=10, first_stage_n=6, first_stage_m=2,
        seed=seed,
    )


# -- model layer -------------------------------------------------------------


class TestScenarioModel:
    def test_strict_json_roundtrip(self):
        slp = _small_storm(5, seed=3)
        d = slp.to_dict()
        text = json.dumps(d, allow_nan=False)  # strict JSON: no inf/nan
        back = ScenarioLP.from_dict(json.loads(text))
        for f in ("A0", "b0", "c0", "T", "W", "b", "c", "probs"):
            np.testing.assert_array_equal(getattr(slp, f), getattr(back, f))
        # Lowered forms agree exactly.
        p1, p2 = slp.to_block_angular(), back.to_block_angular()
        assert (p1.A != p2.A).nnz == 0
        np.testing.assert_array_equal(p1.c, p2.c)
        np.testing.assert_array_equal(p1.rlb, p2.rlb)

    def test_lowering_shape_and_hint(self):
        slp = _small_storm(4, seed=1)
        p = slp.to_block_angular()
        assert sp.issparse(p.A)  # sparse keeps it off the bucketed path
        assert p.m == 2 + 4 * 6 and p.n == 6 + 4 * 10
        h = p.block_structure
        assert h["kind"] == "two_stage" and h["num_blocks"] == 4
        assert h["first_stage_n"] == 6 and h["first_stage_m"] == 2

    def test_lowered_problem_dict_roundtrip_keeps_hint(self):
        # The PR 11 journal serializes requests via LPProblem.to_dict —
        # a scenario job's hint (string kind + int sizes) must survive.
        p = _small_storm(3, seed=2).to_block_angular()
        d = p.to_dict()
        json.dumps(d, allow_nan=False)
        q = LPProblem.from_dict(d)
        assert q.block_structure["kind"] == "two_stage"
        assert int(q.block_structure["num_blocks"]) == 3
        assert (p.A != q.A).nnz == 0

    def test_detection_hint_arrays_survive_dict_roundtrip(self):
        from distributedlpsolver_tpu.models.structure import detect_two_stage

        p = _small_storm(4, seed=5).to_block_angular()
        hint = detect_two_stage(p.A)
        assert hint is not None
        p.block_structure = hint
        q = LPProblem.from_dict(p.to_dict())
        np.testing.assert_array_equal(
            np.asarray(q.block_structure["row_block"]), hint["row_block"]
        )

    def test_k_bucket_ladder(self):
        assert [scenario_k_bucket(k) for k in (1, 2, 3, 4, 5, 8, 9, 33)] == [
            1, 2, 4, 4, 8, 8, 16, 64,
        ]
        with pytest.raises(ValueError):
            scenario_k_bucket(0)

    def test_delta_stream_shares_structure(self):
        from distributedlpsolver_tpu.utils.fingerprint import (
            structural_fingerprint,
        )

        waves = list(scenario_delta_stream(3, num_scenarios=4, seed=7))
        lows = [s.to_block_angular() for s in waves]
        fps = {
            structural_fingerprint(p.A, p.m, p.n, p.lb, p.ub) for p in lows
        }
        assert len(fps) == 1  # b/c-only deltas: one structural key
        # ... but the instances really differ.
        assert not np.array_equal(lows[0].c, lows[1].c)
        # offset= continues the same stream deterministically.
        again = list(
            scenario_delta_stream(1, num_scenarios=4, seed=7, offset=2)
        )[0]
        np.testing.assert_array_equal(again.b, waves[2].b)


# -- decomposed engine vs oracle ---------------------------------------------


class TestScenarioEngine:
    @pytest.mark.parametrize("K", [1, 4, 32])
    def test_matches_lowered_oracle_1e8(self, K):
        from distributedlpsolver_tpu.backends.scenario import solve_scenario

        slp = _small_storm(K, seed=K + 10)
        r = solve_scenario(slp, tol=1e-8)
        assert r.status.value == "optimal"
        lowered = slp.to_block_angular()
        lowered.block_structure = None  # dense path oracle
        rd = ipm_solve(lowered, backend="cpu", tol=1e-8)
        assert rd.status.value == "optimal"
        assert abs(r.objective - rd.objective) <= 1e-8 * (
            1.0 + abs(rd.objective)
        )
        hg = highs_on_general(slp.to_block_angular())
        assert hg.status == 0
        assert abs(r.objective - hg.fun) <= 1e-6 * (1.0 + abs(hg.fun))
        # The solution satisfies the original constraints.
        assert slp.to_block_angular().max_violation(r.x) < 1e-6

    def test_decomposed_solve_matches_dense_M(self):
        """factorize/solve unit check: the two-level Schur elimination +
        preconditioned CG reproduces a dense M⁻¹r at 1e-10."""
        from distributedlpsolver_tpu.backends.scenario import ScenarioBackend
        from distributedlpsolver_tpu.ipm.config import SolverConfig

        slp = _small_storm(8, seed=21)
        inf = to_interior_form(slp.to_block_angular())
        be = ScenarioBackend()
        be.setup(inf, SolverConfig(scale=False))
        A = np.asarray(inf.A.todense())
        rng = np.random.default_rng(0)
        d = 10.0 ** rng.uniform(-3, 3, size=inf.n)
        M = (A * d[None, :]) @ A.T
        r = rng.standard_normal(inf.m)
        got = be._solve(be._factorize(d, 1e-12), r)
        ref = np.linalg.solve(M, r)
        assert np.linalg.norm(got - ref) <= 1e-10 * np.linalg.norm(ref)

    def test_chunked_k_bitwise_stability(self, monkeypatch):
        """Chunked lane processing (K_pad > SCENARIO_CHUNK) is
        deterministic: repeated solves of the same instance through the
        chunked path produce bitwise-identical iterates/solutions."""
        from distributedlpsolver_tpu.backends import scenario as scn

        monkeypatch.setattr(scn, "SCENARIO_CHUNK", 4)
        slp = _small_storm(16, seed=33)
        r1 = scn.solve_scenario(slp, tol=1e-8)
        r2 = scn.solve_scenario(slp, tol=1e-8)
        assert r1.status.value == "optimal"
        assert r1.iterations == r2.iterations
        np.testing.assert_array_equal(r1.x, r2.x)
        rep = scn.last_solve_report()
        assert rep["chunks"] == 4  # 16 lanes / 4 per chunk

    def test_chunked_matches_unchunked(self, monkeypatch):
        from distributedlpsolver_tpu.backends import scenario as scn

        slp = _small_storm(8, seed=34)
        r_full = scn.solve_scenario(slp, tol=1e-8)
        monkeypatch.setattr(scn, "SCENARIO_CHUNK", 2)
        r_chunk = scn.solve_scenario(slp, tol=1e-8)
        assert r_chunk.status.value == "optimal"
        assert abs(r_full.objective - r_chunk.objective) <= 1e-8 * (
            1.0 + abs(r_full.objective)
        )

    def test_zero_recompile_within_k_bucket(self):
        from distributedlpsolver_tpu.backends.scenario import (
            scenario_program_cache_size,
            solve_scenario,
        )

        # Warm the bucket (K_pad = 8) once...
        r = solve_scenario(_small_storm(8, seed=40), tol=1e-8)
        assert r.status.value == "optimal"
        size0 = scenario_program_cache_size()
        # ...then every K in the bucket reuses the same executables.
        for K in (5, 6, 7, 8):
            r = solve_scenario(_small_storm(K, seed=40 + K), tol=1e-8)
            assert r.status.value == "optimal"
        assert scenario_program_cache_size() == size0

    def test_mesh_sharded_lane_axis_matches_unsharded(self):
        from distributedlpsolver_tpu.backends.scenario import ScenarioBackend
        from distributedlpsolver_tpu.parallel import mesh as mesh_lib

        slp = _small_storm(8, seed=50)
        lowered = slp.to_block_angular()
        r0 = ipm_solve(lowered, backend="scenario", tol=1e-8)
        import jax

        mesh = mesh_lib.make_mesh(
            (2,), axis_names=("batch",), devices=jax.devices()[:2]
        )
        r1 = ipm_solve(
            slp.to_block_angular(), backend=ScenarioBackend(mesh=mesh),
            tol=1e-8,
        )
        assert r1.status.value == "optimal"
        assert abs(r0.objective - r1.objective) <= 1e-8 * (
            1.0 + abs(r0.objective)
        )

    def test_operand_footprint_beats_dense(self):
        from distributedlpsolver_tpu.backends.scenario import ScenarioBackend
        from distributedlpsolver_tpu.ipm.config import SolverConfig

        slp = _small_storm(32, seed=60)
        inf = to_interior_form(slp.to_block_angular())
        be = ScenarioBackend()
        be.setup(inf, SolverConfig())
        # The decomposition's stacked operands stay far under the m×m
        # normal matrix the dense path would assemble.
        assert be.operand_nbytes() < inf.m * inf.m * 8

    def test_non_arrow_pattern_fails_setup(self):
        from distributedlpsolver_tpu.backends.scenario import ScenarioBackend
        from distributedlpsolver_tpu.ipm.config import SolverConfig
        from distributedlpsolver_tpu.models.generators import random_sparse_lp

        p = random_sparse_lp(24, 48, density=0.2, seed=1)
        p.block_structure = {
            "kind": "two_stage", "num_blocks": 4, "block_m": 6,
            "block_n": 11, "first_stage_n": 4, "first_stage_m": 0,
        }
        inf = to_interior_form(p)
        be = ScenarioBackend()
        with pytest.raises(ValueError, match="arrow|two_stage"):
            be.setup(inf, SolverConfig())


# -- detection / routing / degradation ---------------------------------------


class TestRoutingAndDegradation:
    def test_detection_regression_auto_routes_hintless(self):
        """Satellite: a lowered ScenarioLP whose hint was stripped still
        auto-routes to the scenario engine off the pattern alone."""
        from distributedlpsolver_tpu.backends.auto import choose_backend_name

        slp = _small_storm(8, seed=70)
        lowered = slp.to_block_angular()
        lowered.block_structure = None
        inf = to_interior_form(lowered)
        for platform in ("cpu", "tpu"):
            name, hint = choose_backend_name(inf, platform, detect=True)
            assert name == "scenario"
            assert hint["kind"] == "two_stage"
            assert hint["num_blocks"] == 8
        r = ipm_solve(lowered, backend="auto", tol=1e-8)
        assert r.status.value == "optimal"
        assert r.backend == "auto(scenario)"

    def test_detection_no_false_positives(self):
        from distributedlpsolver_tpu.models.generators import (
            block_angular_lp,
            random_sparse_lp,
        )
        from distributedlpsolver_tpu.models.structure import detect_two_stage

        assert detect_two_stage(
            random_sparse_lp(300, 600, density=0.01, seed=0).A
        ) is None
        # Primal block-angular (dense linking ROWS) is the other arrow.
        assert detect_two_stage(
            block_angular_lp(8, 16, 24, 8, seed=0, sparse=True).A
        ) is None

    def test_detection_feeds_bordered_precond(self):
        """Satellite: a two_stage detection on a first-stage-row-free
        storm pattern is consumed by the bordered-Woodbury
        preconditioner of the sparse-iterative rung."""
        from distributedlpsolver_tpu.backends.base import get_backend
        from distributedlpsolver_tpu.backends.sparse_iterative import (
            _bordered_usable,
        )
        from distributedlpsolver_tpu.models.generators import storm_sparse_lp
        from distributedlpsolver_tpu.models.structure import detect_two_stage

        p = storm_sparse_lp(16, 32, 48, 24, seed=3)
        hint = detect_two_stage(p.A)
        assert hint is not None and hint["kind"] == "two_stage"
        assert hint["first_stage_m"] == 0
        assert _bordered_usable(hint)
        p.block_structure = hint
        inf = to_interior_form(p)
        be = get_backend("sparse-iterative")
        from distributedlpsolver_tpu.ipm.config import SolverConfig

        be.setup(inf, SolverConfig())
        assert be.precond == "bordered"

    def test_degradation_chain_scenario(self):
        from distributedlpsolver_tpu.backends.auto import degradation_chain

        assert degradation_chain("scenario") == [
            "sparse-iterative", "cpu-sparse", "cpu",
        ]

    def test_supervised_degrades_on_broken_layout(self):
        """A two_stage hint that lies about the pattern fails scenario
        setup and the supervisor finishes the solve on a lower rung —
        never a crash, never a wrong answer."""
        from distributedlpsolver_tpu.models.generators import random_sparse_lp
        from distributedlpsolver_tpu.supervisor import supervised_solve

        p = random_sparse_lp(24, 48, density=0.2, seed=2)
        p.block_structure = {
            "kind": "two_stage", "num_blocks": 4, "block_m": 6,
            "block_n": 11, "first_stage_n": 4, "first_stage_m": 0,
        }
        r = supervised_solve(p, backend="scenario", tol=1e-8)
        assert r.status.value == "optimal"
        hg = highs_on_general(p)
        assert abs(r.objective - hg.fun) <= 1e-6 * (1.0 + abs(hg.fun))


# -- serve semantics ---------------------------------------------------------


class TestScenarioServe:
    def test_delta_wave_warm_cache_amortization(self):
        """Acceptance: across waves of b/c-only deltas the warm cache
        hits (>0 ratio) and the median iterations/request drops
        strictly below the cold median."""
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )

        svc = SolveService(ServiceConfig(flush_s=0.005))
        try:
            futs = [
                svc.submit(s.to_block_angular(), tol=1e-8)
                for s in scenario_delta_stream(
                    10, num_scenarios=8, block_m=6, block_n=10,
                    first_stage_n=6, first_stage_m=2, seed=11,
                )
            ]
            res = [f.result(timeout=180) for f in futs]
        finally:
            svc.shutdown()
        assert all(r.status.value == "optimal" for r in res)
        assert all(r.engine == "scenario" for r in res)
        assert all(r.n_scenarios == 8 and r.scenario_bucket == 8 for r in res)
        warm = [r for r in res if r.warm == "warm"]
        cold = [r for r in res if r.warm != "warm"]
        assert warm and cold  # first request is cold, the wave warms
        med = lambda v: float(np.median(v))
        assert med([r.iterations for r in warm]) < med(
            [r.iterations for r in cold]
        )
        # Decomposition telemetry rides the records.
        assert all(r.schur_ms > 0 for r in res)

    def test_admission_units_controller(self):
        from distributedlpsolver_tpu.net.admission import (
            AdmissionConfig,
            AdmissionController,
            TenantQuota,
        )

        ctl = AdmissionController(
            AdmissionConfig(
                quotas={"acme": TenantQuota(rate=0.001, burst=6.0)}
            ),
            max_depth=64,
        )
        # A K=32 job at k_unit=8 charges 4 units: 6-token burst admits
        # one, rejects the second with reason=quota.
        v1 = ctl.admit("acme", units=4)
        assert v1.admitted
        v2 = ctl.admit("acme", units=4)
        assert not v2.admitted and v2.reason == "quota"
        # in-system accounting is unit-weighted.
        ctl.on_admitted("acme", units=4)
        assert ctl.stats()["acme"]["in_system"] == 4
        ctl.on_finished("acme", units=4)
        assert ctl.stats()["acme"]["in_system"] == 0

    def test_admission_units_under_flood(self):
        """Acceptance: a flood of K-scenario submits is charged
        ceil(K/K_unit) fair-share units each — the quota wall arrives
        units-fast, not request-fast."""
        from distributedlpsolver_tpu.net.admission import (
            AdmissionConfig,
            TenantQuota,
        )
        from distributedlpsolver_tpu.serve.scheduler import ServiceOverloaded
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )

        cfg = ServiceConfig(
            flush_s=0.005,
            scenario_k_unit=8,
            admission=AdmissionConfig(
                quotas={"acme": TenantQuota(rate=0.001, burst=8.0)}
            ),
        )
        svc = SolveService(cfg)
        try:
            slp = _small_storm(32, seed=80)  # 32/8 = 4 units each
            futs = []
            rejected = None
            for _ in range(3):
                try:
                    futs.append(
                        svc.submit(
                            slp.to_block_angular(), tol=1e-8, tenant="acme"
                        )
                    )
                except ServiceOverloaded as e:
                    rejected = e
                    break
            # 8-token burst / 4 units = exactly 2 admitted.
            assert len(futs) == 2
            assert rejected is not None and rejected.reason == "quota"
            for f in futs:
                assert f.result(timeout=180).status.value == "optimal"
            adm = svc.stats()["admission"]["acme"]
            assert adm["admitted"] == 2 and adm["in_system"] == 0
        finally:
            svc.shutdown()

    def test_journal_roundtrip_scenario_job(self, tmp_path):
        """Acceptance: a scenario job admitted to the durable journal by
        a process that dies before solving is replayed by the next one —
        the poll id resolves to an honest OPTIMAL verdict."""
        from distributedlpsolver_tpu.net import protocol
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )

        jd = str(tmp_path / "journal")
        cfg = ServiceConfig(flush_s=0.005, journal_dir=jd)
        # Service A: admit (WAL write) but never start the pipeline —
        # the in-process stand-in for kill -9 between ack and solve.
        svc_a = SolveService(cfg, auto_start=False)
        slp = _small_storm(4, seed=90)
        fut = svc_a.submit(slp.to_block_angular(), tol=1e-8)
        jid = fut.jid
        assert jid
        svc_a._journal.close()
        # Service B on the same journal dir: replay re-enqueues and
        # solves; the poll id survives the restart.
        svc_b = SolveService(cfg)
        try:
            deadline = time.time() + 180
            while time.time() < deadline:
                kind, rec = svc_b.job_result(jid)
                if kind == "done":
                    break
                time.sleep(0.05)
            assert kind == "done"
            assert rec["status"] == "optimal"
            assert rec["n_scenarios"] == 4
            code, body = protocol.payload_from_record(rec)
            assert code == 200 and body["status"] == "optimal"
            # The durable-store payload carries the scenario fields a
            # live-future response would (a restarted front-end's poll
            # answer must not lose the K/bucket/stage split).
            assert body["n_scenarios"] == 4
            assert body["scenario_bucket"] == 4
            assert body["recovered"] is True
        finally:
            svc_b.shutdown()

    @pytest.mark.slow
    def test_kmixed_acceptance_zero_warm_recompiles(self):
        """Acceptance: a 200-request K-mixed stream (buckets 4 and 8)
        runs entirely on warm scenario programs — zero recompiles after
        the two bucket warms — with every verdict OPTIMAL and fair-share
        units stamped.

        Slow tier (PR 17 budget-rebalance precedent): ~30 s of 1-core
        wall for the 200-request soak. The zero-recompile invariant
        itself stays tier-1 via the delta-wave warm-cache test and the
        sparse/bucket zero-recompile families."""
        from distributedlpsolver_tpu.backends.scenario import (
            scenario_program_cache_size,
            solve_scenario,
        )
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )

        # Warm both K buckets (and the delta base's shape) up front —
        # the serve analogue of warm_buckets for the solo scenario path.
        for K in (4, 8):
            solve_scenario(
                two_stage_storm(
                    K, block_m=4, block_n=7, first_stage_n=4,
                    first_stage_m=1, seed=99,
                ),
                tol=1e-8,
            )
        svc = SolveService(ServiceConfig(flush_s=0.002))
        try:
            streams = {
                K: scenario_delta_stream(
                    50, num_scenarios=K, block_m=4, block_n=7,
                    first_stage_n=4, first_stage_m=1, seed=100 + K,
                )
                for K in (3, 4, 6, 8)
            }
            # One cold solve per stream shape to settle program + cache.
            first = {
                K: svc.submit(next(s).to_block_angular(), tol=1e-8)
                for K, s in streams.items()
            }
            for f in first.values():
                assert f.result(timeout=180).status.value == "optimal"
            size0 = scenario_program_cache_size()
            futs = []
            order = [3, 4, 6, 8]
            for i in range(49):
                for K in order:
                    futs.append(
                        svc.submit(
                            next(streams[K]).to_block_angular(), tol=1e-8
                        )
                    )
            res = [f.result(timeout=600) for f in futs]
        finally:
            svc.shutdown()
        assert len(res) == 196  # + 4 warmers = 200 requests through serve
        assert all(r.status.value == "optimal" for r in res)
        assert scenario_program_cache_size() == size0  # ZERO recompiles
        buckets = {r.scenario_bucket for r in res}
        assert buckets == {4, 8}
        # Warm-cache amortization at steady state.
        warm_frac = sum(1 for r in res if r.warm == "warm") / len(res)
        assert warm_frac > 0.5

    def test_http_scenarios_payload(self):
        from distributedlpsolver_tpu.net import protocol

        # Generated form.
        body = json.dumps(
            {
                "scenarios": {
                    "n_scenarios": 4, "seed": 2, "block_m": 4,
                    "block_n": 7, "first_stage_n": 4, "first_stage_m": 1,
                },
                "tol": 1e-6,
                "tenant": "acme",
            }
        ).encode()
        req = protocol.parse_solve_request(body)
        assert req.problem.block_structure["kind"] == "two_stage"
        assert req.problem.block_structure["num_blocks"] == 4
        assert req.tol == 1e-6 and req.tenant == "acme"
        # Explicit base + deltas (ScenarioLP.to_dict form).
        slp = _small_storm(3, seed=4)
        body = json.dumps({"scenarios": slp.to_dict()}).encode()
        req2 = protocol.parse_solve_request(body)
        assert req2.problem.m == slp.m and req2.problem.n == slp.n
        # Malformed: 400 path.
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_solve_request(
                json.dumps({"scenarios": {"bogus": 1}}).encode()
            )
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_solve_request(
                json.dumps({"scenarios": {"n_scenarios": 0}}).encode()
            )

    def test_http_end_to_end_scenario_solve(self):
        from distributedlpsolver_tpu.net.server import (
            NetConfig,
            SolveHTTPServer,
        )
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )
        import urllib.request

        svc = SolveService(ServiceConfig(flush_s=0.005))
        front = SolveHTTPServer(svc, NetConfig()).start()
        try:
            body = json.dumps(
                {
                    "scenarios": {
                        "n_scenarios": 4, "seed": 5, "block_m": 4,
                        "block_n": 7, "first_stage_n": 4,
                        "first_stage_m": 1,
                    }
                }
            ).encode()
            req = urllib.request.Request(
                front.url + "/v1/solve", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=180) as resp:
                payload = json.loads(resp.read())
            assert payload["status"] == "optimal"
            assert payload["n_scenarios"] == 4
            assert payload["scenario_bucket"] == 4
            assert payload["schur_ms"] >= 0
        finally:
            front.shutdown()
            svc.shutdown()


# -- obs wiring --------------------------------------------------------------


class TestScenarioObs:
    def test_metrics_and_report_reconcile_with_stats(self, tmp_path):
        from distributedlpsolver_tpu.obs import metrics as obs_metrics
        from distributedlpsolver_tpu.obs.report import report_from_paths
        from distributedlpsolver_tpu.serve.service import (
            ServiceConfig,
            SolveService,
        )

        log = str(tmp_path / "serve.jsonl")
        reg = obs_metrics.MetricsRegistry()
        svc = SolveService(
            ServiceConfig(flush_s=0.005, log_jsonl=log), metrics=reg
        )
        try:
            futs = [
                svc.submit(_small_storm(K, seed=K).to_block_angular(),
                           tol=1e-8)
                for K in (3, 4, 8)
            ]
            for f in futs:
                assert f.result(timeout=180).status.value == "optimal"
            stats = svc.stats()
        finally:
            svc.shutdown()
        # Metrics: solves by terminal engine, K histogram, stage walls.
        snap = reg.snapshot()
        solves = sum(
            v for k, v in snap.items()
            if k.startswith("scenario_solves_total")
        )
        assert solves == 3
        k_hist = snap.get("scenario_k")
        assert k_hist and k_hist["count"] == 3
        assert snap["scenario_schur_ms"]["sum"] > 0
        # Report table reconciles with SolveService.stats().
        rep = report_from_paths([log])
        assert rep["scenario"]["solves"] == stats["scenario"]["solves"] == 3
        for bucket, row in rep["scenario"]["by_bucket"].items():
            srow = stats["scenario"]["by_bucket"][bucket]
            assert row["count"] == srow["count"]
            # The report's percentile runs over JSONL values already
            # rounded to 3 decimals while stats() rounds the percentile
            # of raw floats — a value near a 0.0005 grid boundary lands
            # one 0.001 step apart, so the tolerance must cover a full
            # grid step with float-repr slack.
            assert row["total_ms"]["p50"] == pytest.approx(
                srow["total_ms_p50"], abs=2e-3
            )
        from distributedlpsolver_tpu.obs.report import render

        text = render(rep)
        assert "scenario tier: 3 solves" in text
