* Golden fixture: OBJSENSE section-body form + negative RHS on a G row.
* Hand-derived optimum: A=3, B=1, objective 14.0 (maximized).
NAME MAXI
OBJSENSE
    MAX
ROWS
 N  PROFIT
 L  CAP
 G  FLOOR
COLUMNS
    A  PROFIT  3.0  CAP  2.0
    A  FLOOR  1.0
    B  PROFIT  5.0  CAP  4.0
RHS
    R  CAP  10.0  FLOOR  -2.0
BOUNDS
 UP B1  A  3.0
 UP B1  B  2.0
ENDATA
