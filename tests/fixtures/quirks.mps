* Golden fixture: every classic MPS quirk in one file.
* Hand-derived optimum: x = (-1.5, 1.5, 0.5, 1.5), objective 12.0
* (see tests/test_fixtures.py for the derivation).
NAME QUIRKS
ROWS
 N  COST
 N  FREEROW
 L  LIM1
 G  LIM2
 E  EQ1
 E  EQ2
COLUMNS
    X1  COST  1.0  LIM1  1.0
    X1  LIM2  1.0
    X1  FREEROW  3.0
    X2  COST  2.0  EQ1  1.0
    X2  LIM1  1.0
    X3  EQ1  1.0  EQ2  1.0
    X3  COST  0.5
    X3  COST  0.5
    X4  EQ2  1.0  LIM2  1.0
RHS
    RHS1  COST  -10.0
    RHS1  LIM1  4.0
    RHS1  EQ1  2.0
    RHS1  EQ2  3.0
RANGES
    RNG1  LIM1  4.0
    RNG1  LIM2  3.0
    RNG1  EQ1  1.5
    RNG1  EQ2  -1.0
BOUNDS
 UP BND1  X1  -1.0
 MI BND1  X2
 UP BND1  X2  5.0
 FX BND1  X4  1.5
ENDATA
