"""Distributed-backend tests on 8 virtual CPU devices (SURVEY.md §4).

The analogue of the reference's single-machine ``mpirun -np N``
equivalence tests (1-rank vs 4-rank must agree, SURVEY.md §4): the same
problem solved on a 1-device and an 8-device mesh must converge to the
same optimum, and the compiled step must actually contain the all-reduce
that replaces the reference's per-iteration ``MPI_Allreduce``
(BASELINE.json:5).
"""

import jax
import numpy as np
import pytest

from distributedlpsolver_tpu.backends import get_backend
from distributedlpsolver_tpu.ipm import SolverConfig, Status, solve
from distributedlpsolver_tpu.models.generators import random_dense_lp, random_general_lp
from distributedlpsolver_tpu.parallel import make_mesh
from tests.oracle import highs_on_general

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def test_mesh_construction():
    m = make_mesh()
    assert m.devices.size == len(jax.devices())
    m2 = make_mesh((4, 2), axis_names=("cols", "rows"))
    assert m2.shape == {"cols": 4, "rows": 2}
    with pytest.raises(ValueError):
        make_mesh((3,))


@pytest.mark.parametrize("seed", [0, 3])
def test_sharded_matches_dense(seed):
    p = random_dense_lp(24, 64, seed=seed)
    r1 = solve(p, backend="tpu", max_iter=60)
    r8 = solve(p, backend="sharded", max_iter=60)
    assert r8.status == Status.OPTIMAL, r8.summary()
    assert r8.objective == pytest.approx(r1.objective, rel=1e-7, abs=1e-7)
    hi = highs_on_general(p)
    assert abs(r8.objective - hi.fun) <= 2e-6 * (1 + abs(hi.fun))


def test_sharded_general_form():
    p = random_general_lp(20, 40, seed=1)
    r8 = solve(p, backend="sharded", max_iter=60)
    hi = highs_on_general(p)
    assert r8.status == Status.OPTIMAL
    assert abs(r8.objective - hi.fun) <= 2e-6 * (1 + abs(hi.fun))


def test_uneven_shard_sizes():
    """n not divisible by the mesh size — GSPMD pads; results must agree."""
    p = random_dense_lp(15, 37, seed=2)  # 37+15 slack-free cols, not %8
    r1 = solve(p, backend="tpu", max_iter=60)
    r8 = solve(p, backend="sharded", max_iter=60)
    assert r8.status == Status.OPTIMAL
    assert r8.objective == pytest.approx(r1.objective, rel=1e-7, abs=1e-7)


def test_compiled_step_contains_all_reduce():
    """The sharded contraction (A*d)@A.T must lower to per-shard GEMMs plus
    an all-reduce over the mesh — the compiler-inserted replacement for the
    reference's MPI_Allreduce of Schur blocks (BASELINE.json:5)."""
    from distributedlpsolver_tpu.backends.dense import _dense_step
    from distributedlpsolver_tpu.models.problem import to_interior_form
    import jax.numpy as jnp

    p = random_dense_lp(16, 32, seed=0)
    inf = to_interior_form(p)
    cfg = SolverConfig()
    be = get_backend("sharded")
    be.setup(inf, cfg)
    st = be.starting_point()
    lowered = _dense_step.lower(
        be._A,
        be._data,
        st,
        jnp.asarray(cfg.reg_dual, be._dtype),
        be._params,
        be._factor_dtype_name,
        be._refine,
    )
    hlo = lowered.compile().as_text()
    assert "all-reduce" in hlo, "sharded step compiled without any collective"


def test_sharded_state_is_distributed():
    p = random_dense_lp(16, 32, seed=0)
    from distributedlpsolver_tpu.models.problem import to_interior_form

    be = get_backend("sharded")
    be.setup(to_interior_form(p), SolverConfig())
    st = be.starting_point()
    assert len(st.x.sharding.device_set) == 8
    assert len(st.y.sharding.device_set) == 8  # replicated across all 8
    host = be.to_host(st)
    assert isinstance(np.asarray(host.x), np.ndarray)
