"""Durable job journal tests (README "Durability & graceful shutdown"):
WAL round trip, torn-tail tolerance, compaction, result-store bounds,
crash recovery through SolveService (honest TIMEOUT for dead deadlines,
fingerprint-idempotent resubmits), graceful drain, and a REAL kill -9
crash-restart of an HTTP front-end against the same journal directory.

All CPU; the crash-restart test spawns actual `cli serve-http`
processes on ephemeral ports.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.models.problem import LPProblem
from distributedlpsolver_tpu.serve import ServiceConfig, SolveService
from distributedlpsolver_tpu.serve.journal import (
    JobJournal,
    request_fingerprint,
    request_spec,
)
from distributedlpsolver_tpu.serve.scheduler import ServiceOverloaded

pytestmark = pytest.mark.chaos


def _spec(seed=0, tol=1e-8, tenant="acme", name=None):
    p = random_dense_lp(8, 24, seed=seed)
    return request_spec(
        p, tol=tol, tenant=tenant, priority="normal",
        name=name or f"j{seed}",
    )


# -- problem serialization ---------------------------------------------------


def test_problem_dict_roundtrip_dense_and_bounds():
    p = random_dense_lp(6, 15, seed=3)
    q = LPProblem.from_dict(p.to_dict())
    assert q.m == p.m and q.n == p.n
    np.testing.assert_allclose(q.c, p.c)
    np.testing.assert_allclose(q.A, p.A)
    np.testing.assert_allclose(q.rlb, p.rlb)
    # Infinities survive the strict-JSON encoding (string sentinels).
    blob = json.dumps(p.to_dict())
    r = LPProblem.from_dict(json.loads(blob))
    assert np.all(np.isposinf(r.ub) == np.isposinf(p.ub))


def test_problem_dict_roundtrip_sparse_stays_sparse():
    import scipy.sparse as sp

    A = sp.random(10, 20, density=0.15, random_state=0, format="csr")
    p = LPProblem(
        c=np.ones(20), A=A, rlb=np.zeros(10), rub=np.full(10, 5.0),
        lb=np.zeros(20), ub=np.full(20, np.inf),
    )
    q = LPProblem.from_dict(json.loads(json.dumps(p.to_dict())))
    assert sp.issparse(q.A)
    np.testing.assert_allclose(q.A.toarray(), A.toarray())


# -- WAL mechanics -----------------------------------------------------------


def test_journal_admit_finish_replay_roundtrip(tmp_path):
    d = str(tmp_path / "j")
    j = JobJournal(d)
    s1, s2 = _spec(1), _spec(2)
    j1 = j.admit(s1, request_fingerprint(s1), "acme", "normal", None)
    j2 = j.admit(s2, request_fingerprint(s2), "acme", "high", None)
    j.mark(j1, "dispatched")
    j.finish(j1, {"status": "optimal", "id": 1}, "optimal")
    j.close()

    j_r = JobJournal(d)
    rep = j_r.replay()
    assert [job.jid for job in rep.unfinished] == [j2]
    assert rep.finished == 1 and rep.torn == 0
    assert j_r.result(j1)["status"] == "optimal"
    assert j_r.is_pending(j2)
    # Sequence continues past the replayed max: no id reuse.
    s3 = _spec(3)
    j3 = j_r.admit(s3, request_fingerprint(s3), "acme", "normal", None)
    assert j3 not in (j1, j2)
    j_r.close()


def test_journal_torn_tail_skipped_with_count(tmp_path):
    d = str(tmp_path / "j")
    j = JobJournal(d)
    s = _spec(1)
    jid = j.admit(s, request_fingerprint(s), "t", "normal", None)
    j.close()
    # Byte-truncate the final record: the crash-mid-write artifact.
    path = os.path.join(d, "journal.jsonl")
    with open(path, "ab") as fh:
        fh.write(b'{"j": "admitted", "jid": "jto')
    j_r = JobJournal(d)
    rep = j_r.replay()
    assert rep.torn == 1
    assert [job.jid for job in rep.unfinished] == [jid]
    j_r.close()


def test_journal_result_file_outranks_torn_finished_record(tmp_path):
    """A crash can tear off the `finished` WAL record after the result
    file landed (rename is atomic): replay must treat the job as done —
    re-enqueueing it would be the duplicate solve."""
    d = str(tmp_path / "j")
    j = JobJournal(d)
    s = _spec(1)
    jid = j.admit(s, request_fingerprint(s), "t", "normal", None)
    j.finish(jid, {"status": "optimal"}, "optimal")
    j.close()
    # Cut the finished record off the WAL tail.
    path = os.path.join(d, "journal.jsonl")
    lines = open(path, "rb").read().splitlines(keepends=True)
    with open(path, "wb") as fh:
        fh.writelines(lines[:-1])
    j_r = JobJournal(d)
    assert j_r.replay().unfinished == []
    assert j_r.result(jid)["status"] == "optimal"
    j_r.close()


def test_journal_finish_idempotent(tmp_path):
    j = JobJournal(str(tmp_path / "j"))
    s = _spec(1)
    jid = j.admit(s, request_fingerprint(s), "t", "normal", None)
    assert j.finish(jid, {"status": "optimal", "try": 1}, "optimal")
    assert not j.finish(jid, {"status": "optimal", "try": 2}, "optimal")
    assert j.result(jid)["try"] == 1
    j.close()


def test_journal_compaction_bounds_the_wal(tmp_path):
    d = str(tmp_path / "j")
    j = JobJournal(d, compact_every=40)
    keep = None
    for k in range(30):
        s = _spec(k)
        jid = j.admit(s, request_fingerprint(s), "t", "normal", None)
        if k == 29:
            keep = jid  # left unfinished
        else:
            j.finish(jid, {"status": "optimal"}, "optimal")
    path = os.path.join(d, "journal.jsonl")
    n_lines = sum(1 for _ in open(path))
    # Compaction rewrote: only meta + the unfinished admit (+ maybe a
    # handful of post-compaction records) survive, not ~90 records.
    assert n_lines < 30
    j_r = JobJournal(d)
    assert [job.jid for job in j_r.replay().unfinished] == [keep]
    j_r.close()
    j.close()


def test_journal_result_store_evicts_resolved_only(tmp_path):
    j = JobJournal(str(tmp_path / "j"), results_cap=5)
    jids = []
    for k in range(9):
        s = _spec(k)
        jid = j.admit(s, request_fingerprint(s), "t", "normal", None)
        j.finish(jid, {"status": "optimal", "k": k}, "optimal")
        jids.append(jid)
    # Oldest resolved results evicted; newest kept; pending untouched.
    assert j.result(jids[0]) is None
    assert j.result(jids[-1])["k"] == 8
    assert j.stats()["results"] == 5
    j.close()


def test_journal_write_fault_counts_and_degrades(tmp_path, monkeypatch):
    monkeypatch.setenv("DLPS_JOURNAL_FAIL_AFTER", "2")
    j = JobJournal(str(tmp_path / "j"))
    s = _spec(1)
    jid = j.admit(s, request_fingerprint(s), "t", "normal", None)  # write 2 fails
    assert j.write_errors == 1
    # The journal keeps serving: later writes land.
    j.finish(jid, {"status": "optimal"}, "optimal")
    assert j.result(jid)["status"] == "optimal"
    j.close()


# -- service-level recovery --------------------------------------------------


def _svc(journal_dir, **kw):
    return SolveService(
        ServiceConfig(
            batch=4, flush_s=0.02, journal_dir=str(journal_dir), **kw
        )
    )


def test_service_journal_roundtrip_and_poll_rebinding(tmp_path):
    svc = _svc(tmp_path / "j")
    try:
        fut = svc.submit(random_dense_lp(8, 24, seed=1), name="a")
        jid = fut.jid
        assert jid is not None
        assert fut.result(timeout=120).status is Status.OPTIMAL
        kind, rec = svc.job_result(jid)
        assert kind == "done" and rec["status"] == "optimal"
        assert rec["x"] is not None and len(rec["x"]) == 24
    finally:
        svc.shutdown()
    # A RESTARTED service against the same dir re-binds the poll id.
    svc2 = _svc(tmp_path / "j")
    try:
        kind, rec = svc2.job_result(jid)
        assert kind == "done" and rec["status"] == "optimal"
        assert svc2.job_result("jnope-1")[0] == "unknown"
    finally:
        svc2.shutdown()


def test_service_replays_unfinished_and_times_out_dead_deadlines(tmp_path):
    d = tmp_path / "j"
    # Forge a crashed service's WAL: one live job, one whose wall-clock
    # deadline died with the process.
    j = JobJournal(str(d))
    s_live = _spec(5, name="live")
    jid_live = j.admit(
        s_live, request_fingerprint(s_live), "acme", "normal", None
    )
    s_dead = _spec(6, name="dead")
    jid_dead = j.admit(
        s_dead, request_fingerprint(s_dead), "acme", "normal",
        time.time() - 30.0,
    )
    j.close()

    svc = _svc(d)
    try:
        assert svc.drain(timeout=300)
        kind, rec = svc.job_result(jid_live)
        assert kind == "done" and rec["status"] == "optimal"
        kind, rec = svc.job_result(jid_dead)
        assert kind == "done" and rec["status"] == "timeout"
        # Honest verdict carries the journal fault attribution.
        assert any(f["backend"] == "journal" for f in rec["faults"])
    finally:
        svc.shutdown()


def test_resubmit_attaches_to_replayed_job_fingerprint_idempotent(tmp_path):
    d = tmp_path / "j"
    j = JobJournal(str(d))
    s = _spec(9, name="dup")
    jid = j.admit(s, request_fingerprint(s), "acme", "normal", None)
    j.close()

    svc = SolveService(
        ServiceConfig(batch=4, flush_s=0.05, journal_dir=str(d)),
        auto_start=False,  # keep the replayed job queued
    )
    try:
        # The client's crash-retry of the same request: SAME problem,
        # tol, tenant, name — attaches to the replayed job's future
        # instead of solving twice.
        p = LPProblem.from_dict(s["problem"])
        fut = svc.submit(
            p, tol=1e-8, tenant="acme", priority="normal", name="dup"
        )
        assert fut.jid == jid
        # A DIFFERENT request does not dedupe.
        fut2 = svc.submit(random_dense_lp(8, 24, seed=77), name="other")
        assert fut2.jid != jid
        svc.start()
        assert fut.result(timeout=120).status is Status.OPTIMAL
        # Exactly one finished record for the deduped jid.
        wal = os.path.join(str(d), "journal.jsonl")
        finishes = [
            r for r in map(json.loads, open(wal))
            if r.get("j") == "finished" and r.get("jid") == jid
        ]
        assert len(finishes) == 1
    finally:
        svc.shutdown()


def test_drain_for_shutdown_sheds_and_finishes(tmp_path):
    svc = _svc(tmp_path / "j")
    try:
        futs = [
            svc.submit(random_dense_lp(8, 24, seed=k)) for k in range(6)
        ]
        assert not svc.draining
        svc.begin_draining()
        assert svc.draining
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(random_dense_lp(8, 24, seed=99))
        assert ei.value.reason == "draining"
        assert ei.value.retry_after_s > 0
        assert svc.drain_for_shutdown(timeout=300)
        assert all(
            f.result(timeout=5).status is Status.OPTIMAL for f in futs
        )
        assert svc.stats()["draining"] is True
    finally:
        svc.shutdown(drain=False)


# -- the real thing: kill -9 a front-end mid-stream, restart, recover --------


def test_kill9_frontend_restart_resolves_every_poll_url(tmp_path):
    """Crash-restart acceptance: a REAL serve-http process is
    SIGKILLed mid-stream; a restart against the same journal_dir must
    re-bind every issued poll URL, complete (or honestly time out) the
    re-enqueued work, and never solve a journal-replayed request
    twice."""
    from distributedlpsolver_tpu.net.chaos import (
        ChaosPlane,
        free_port,
        journal_duplicate_solves,
    )

    plane = ChaosPlane(str(tmp_path))
    ladder = str(tmp_path / "ladder.json")
    with open(ladder, "w") as fh:
        fh.write(json.dumps([{"m": 8, "n": 24, "batch": 4}]))
    proc = plane.spawn_backend(
        "be", port=free_port(), buckets_json=ladder,
        extra_flags=["--flush-ms", "20", "--batch", "4"],
    )
    try:
        assert plane.wait_ready(proc, 180), "backend never came up"

        def post(body):
            req = urllib.request.Request(
                proc.url + "/v1/solve",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        ids = []
        for k in range(12):
            code, out = post(
                {"m": 8, "n": 24, "seed": k, "async": True,
                 "id": f"crash-{k}"}
            )
            assert code == 202
            ids.append(out["id"])
        # Mid-stream: no drain, no flush courtesy — SIGKILL.
        plane.kill9("be")
        plane.restart("be")  # same port, same journal_dir

        deadline = time.monotonic() + 120
        unresolved = set(ids)
        statuses = {}
        while unresolved and time.monotonic() < deadline:
            for rid in list(unresolved):
                try:
                    with urllib.request.urlopen(
                        proc.url + f"/v1/solve/{rid}", timeout=10
                    ) as r:
                        code, out = r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    code, out = e.code, json.loads(e.read())
                except (urllib.error.URLError, OSError):
                    break  # restart still settling
                if code != 202:
                    statuses[out.get("status")] = (
                        statuses.get(out.get("status"), 0) + 1
                    )
                    unresolved.discard(rid)
            time.sleep(0.1)
        assert not unresolved, (
            f"acknowledged poll URLs lost across restart: {unresolved}"
        )
        # Honest verdicts only, and no journal-replayed double solves.
        assert set(statuses) <= {"optimal", "timeout"}
        assert statuses.get("optimal", 0) >= 1
        assert journal_duplicate_solves(proc.journal_dir) == 0
    finally:
        plane.shutdown_all()
