"""Closed-loop elasticity tests (README "Elasticity & overload
protection"): the brownout ladder's staged engage/escalate/release
machine, the router's per-backend circuit breaker, the
ElasticController's hysteresis/cooldown/flap-damped scale decisions,
the scale-in-under-load drain (outstanding async polls resolve through
the router fan-out while the victim drains), and the
probe_elastic_serve.py tier-1 smoke — the chaos-elasticity acceptance
run (load ramp + kill -9 mid-scale over a live multi-process plane).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from distributedlpsolver_tpu.net.admission import (
    BROWNOUT_STAGES,
    BrownoutConfig,
    BrownoutController,
)
from distributedlpsolver_tpu.net.router import Router, RouterConfig
from distributedlpsolver_tpu.obs.metrics import MetricsRegistry
from distributedlpsolver_tpu.serve.elastic import (
    ElasticConfig,
    ElasticController,
)

pytestmark = pytest.mark.elastic_serve

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- brownout ladder ----------------------------------------------------------


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


def _brownout(**kw):
    clock = FakeClock()
    cfg = BrownoutConfig(
        engage_after_s=1.0, escalate_after_s=2.0, release_after_s=2.0, **kw
    )
    return (
        BrownoutController(
            cfg, max_depth=100, metrics=MetricsRegistry(), clock=clock
        ),
        clock,
    )


def test_brownout_engages_only_after_sustained_saturation():
    bo, clock = _brownout()
    # Instantaneous spike: no stage.
    assert bo.observe(90) == []
    assert bo.stage() == 0
    clock.tick(0.5)
    assert bo.observe(90) == []
    # Sustained past engage_after_s: stage 1, shed_batch.
    clock.tick(0.6)
    evs = bo.observe(90)
    assert [e["event"] for e in evs] == ["brownout_enter"]
    assert evs[0]["stage"] == 1 and evs[0]["reason"] == "queue_depth"
    assert bo.stage() == 1
    assert BROWNOUT_STAGES[1] == "shed_batch"


def test_brownout_spike_between_watermarks_holds_and_resets_clocks():
    bo, clock = _brownout()
    bo.observe(90)
    clock.tick(0.9)  # almost engaged...
    bo.observe(60)  # ...but a between-watermark dip resets the clock
    clock.tick(0.2)
    assert bo.observe(90) == []  # fresh sustain window
    assert bo.stage() == 0


def test_brownout_sheds_batch_only_and_escalates_rungs():
    bo, clock = _brownout()
    bo.observe(90)
    clock.tick(1.1)
    bo.observe(90)
    assert bo.stage() == 1
    assert bo.should_shed("batch")
    assert not bo.should_shed("normal")
    assert not bo.should_shed("high")
    assert bo.flush_widen() == 1.0  # stage 1: no flush widening yet
    assert not bo.reroute_pdhg(1e-3)
    # Continued saturation: stage 2 widens the flush window.
    clock.tick(2.1)
    evs = bo.observe(90)
    assert evs and evs[0]["stage"] == 2
    assert bo.flush_widen() == BrownoutConfig().flush_widen
    # Stage 3 re-routes tol-eligible traffic only: the tol floor is a
    # hard correctness line.
    clock.tick(2.1)
    assert bo.observe(90)[0]["stage"] == 3
    assert bo.reroute_pdhg(1e-4)
    assert not bo.reroute_pdhg(1e-9)
    assert bo.stats()["stage_name"] == "pdhg_reroute"
    assert bo.stats()["sheds"] == 1


def test_brownout_releases_one_stage_per_sustained_calm_window():
    bo, clock = _brownout()
    bo.observe(90)
    clock.tick(1.1)
    bo.observe(90)
    clock.tick(2.1)
    bo.observe(90)
    assert bo.stage() == 2
    # Calm must SUSTAIN release_after_s per released stage.
    bo.observe(10)
    clock.tick(1.0)
    assert bo.observe(10) == []
    clock.tick(1.1)
    evs = bo.observe(10)
    assert evs and evs[0]["event"] == "brownout_exit"
    assert bo.stage() == 1
    clock.tick(2.1)
    evs = bo.observe(10)
    assert evs[0]["stage"] == 0
    assert "ms" in evs[0]  # full-episode duration stamped on the exit
    assert bo.stage() == 0
    # Fully released: nothing sheds.
    assert not bo.should_shed("batch")


def test_brownout_reject_rate_triggers_engagement():
    bo, clock = _brownout()
    # Non-brownout rejections at 3/s with a calm queue: saturation.
    for _ in range(3):
        bo.note_reject()
    bo.observe(0)
    clock.tick(1.1)
    for _ in range(3):
        bo.note_reject()
    evs = bo.observe(0)
    assert evs and evs[0]["reason"] == "reject_rate"
    assert bo.stats()["reject_rate"] >= 3.0


# -- circuit breaker ----------------------------------------------------------


def _router(**kw):
    cfg = RouterConfig(
        breaker_window=8,
        breaker_min_samples=4,
        breaker_error_rate=0.5,
        breaker_hold_base_s=1.0,
        breaker_hold_cap_s=30.0,
        **kw,
    )
    r = Router(["http://127.0.0.1:9"], cfg, metrics=MetricsRegistry())
    st = r._backends["http://127.0.0.1:9"]
    st.healthy = True  # as if probes pass (the flapping-backend shape)
    return r, st


def test_breaker_trips_on_error_rate_and_takes_backend_out():
    r, st = _router()
    url = st.url
    assert r.pick() == url
    r._release(url)
    # Below min_samples: no trip even at 100% errors.
    for _ in range(3):
        r._record_forward_outcome(url, False)
    assert st.breaker == "closed"
    r._record_forward_outcome(url, False)
    assert st.breaker == "open"
    assert st.breaker_trips == 1
    # Open = out of rotation even though probes still pass.
    assert r.pick() is None
    row = next(b for b in r.statusz()["backends"] if b["url"] == url)
    assert row["breaker"] == "open" and row["breaker_trips"] == 1
    snap = r.metrics.snapshot()
    assert snap.get("router_breaker_opens_total") == 1


def test_breaker_mixed_window_below_threshold_stays_closed():
    r, st = _router()
    for ok in (True, False, True, True, False, True, True, True):
        r._record_forward_outcome(st.url, ok)
    assert st.breaker == "closed"  # 2/8 errors < 0.5


def test_breaker_half_open_admits_one_trial_then_closes_on_success():
    r, st = _router()
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    assert st.breaker == "open" and st.breaker_hold_s > 0
    # Hold not yet elapsed: still out.
    assert r.pick() is None
    st.breaker_until = 0.0  # hold elapsed
    assert r.pick() == st.url  # the single half-open trial
    assert st.breaker == "half_open" and st.breaker_probe_live
    assert r.pick() is None  # trial in flight: nobody else routes here
    r._release(st.url)
    r._record_forward_outcome(st.url, True)
    assert st.breaker == "closed"
    assert r.pick() == st.url  # back in normal rotation


def test_breaker_failed_trial_reopens_with_escalated_hold():
    r, st = _router()
    # Trip once, recover through a successful half-open trial...
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    hold1 = st.breaker_hold_s
    st.breaker_until = 0.0
    assert r.pick() == st.url
    r._release(st.url)
    r._record_forward_outcome(st.url, True)
    assert st.breaker == "closed" and st.breaker_closed_at > 0
    # ...then re-trip soon after the close: the streak escalates and
    # the doubled base hold beats the jitter band of the first one.
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    assert st.breaker == "open"
    assert st.breaker_trips == 2 and st.breaker_streak == 2
    assert st.breaker_hold_s > hold1


def test_breaker_streak_resets_without_recent_close():
    r, st = _router()
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    st.breaker_until = 0.0
    assert r.pick() == st.url
    r._release(st.url)
    r._record_forward_outcome(st.url, False)  # trial died: re-open
    assert st.breaker == "open" and st.breaker_trips == 2
    # Never closed since construction: no close stamp, so the streak
    # stays at 1 (escalation keys off re-trips after a close).
    assert st.breaker_streak == 1


def test_breaker_disabled_never_records():
    r, st = _router(breaker_enabled=False)
    for _ in range(8):
        r._record_forward_outcome(st.url, False)
    assert st.breaker == "closed" and st.outcomes == []


def test_breaker_half_open_ignores_stale_forward_outcome():
    r, st = _router()
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    st.breaker_until = 0.0
    url, is_trial = r._pick_attributed()
    assert url == st.url and is_trial  # the single admitted trial
    r._release(st.url)
    # A slow forward dispatched BEFORE the trip lands while the trial
    # is still in flight: explicitly attributed as not-the-trial, it
    # must neither close the breaker nor consume the trial slot.
    r._record_forward_outcome(st.url, True, trial=False)
    assert st.breaker == "half_open" and st.breaker_probe_live
    r._record_forward_outcome(st.url, False, trial=False)
    assert st.breaker == "half_open" and st.breaker_probe_live
    # The real trial's verdict still resolves it.
    r._record_forward_outcome(st.url, True, trial=True)
    assert st.breaker == "closed"


def test_breaker_half_open_unattributed_outcome_needs_live_probe():
    r, st = _router()
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    st.breaker = "half_open"
    st.breaker_probe_live = False  # hold elapsed, no trial admitted yet
    # Unattributed outcome with no trial in flight = stale evidence.
    r._record_forward_outcome(st.url, True)
    assert st.breaker == "half_open"


def test_breaker_trial_draining_releases_probe_slot():
    r, st = _router()
    st.ready = True
    for _ in range(4):
        r._record_forward_outcome(st.url, False)
    st.breaker_until = 0.0
    url, is_trial = r._pick_attributed()
    assert url == st.url and is_trial
    r._release(st.url)
    # The trial forward came back with a backend-stamped draining 503:
    # no breaker verdict, but the trial slot must be released or the
    # backend is pinned out of rotation forever.
    r._note_draining(st.url, trial=is_trial)
    assert not st.breaker_probe_live and st.breaker == "half_open"
    assert not st.ready
    st.ready = True  # the poll loop readmits after /readyz recovers
    assert r.pick() == st.url  # a fresh trial routes here again


# -- controller decisions (no processes: observe/spawn/drain stubbed) --------


def _ctl(tmp_path, **kw):
    defaults = dict(
        registry_path=str(tmp_path / "reg.json"),
        min_backends=1,
        max_backends=3,
        out_sustain_s=0.0,
        in_sustain_s=0.0,
        cooldown_s=0.0,
        flap_window_s=60.0,
        flap_max_actions=100,
        workdir=str(tmp_path),
    )
    defaults.update(kw)
    ctl = ElasticController(
        ElasticConfig(**defaults), metrics=MetricsRegistry()
    )
    calls = []
    ctl._spawn_one = lambda reason: calls.append(("spawn", reason))
    ctl._shrink_one = lambda reason: calls.append(("drain", reason))
    return ctl, calls


def _obs(**kw):
    base = dict(
        now=time.perf_counter(),
        n_live=1,
        n_ready=1,
        mean_load=2.0,
        reject_rate=0.0,
        brownout_stage=0,
        p99_ms=None,
    )
    base.update(kw)
    return base


def test_controller_scales_out_on_queue_depth_and_attributes_reason(
    tmp_path,
):
    ctl, calls = _ctl(tmp_path)
    ctl._observe = lambda: _obs(mean_load=20.0)
    ctl.step()
    assert ctl.target() == 2
    assert calls == [("spawn", "queue_depth")]


def test_controller_signal_priority_and_reasons(tmp_path):
    ctl, _ = _ctl(tmp_path)
    assert ctl._signal_reason(_obs(brownout_stage=2)) == "brownout"
    assert ctl._signal_reason(_obs(reject_rate=5.0)) == "reject_rate"
    assert ctl._signal_reason(_obs(mean_load=99.0)) == "queue_depth"
    assert ctl._signal_reason(_obs()) is None
    ctl2, _ = _ctl(tmp_path, p99_high_ms=500.0)
    assert ctl2._signal_reason(_obs(p99_ms=900.0)) == "p99"
    assert ctl2._signal_reason(_obs(p99_ms=100.0)) is None


def test_controller_out_sustain_gates_one_burst_one_step(tmp_path):
    ctl, calls = _ctl(tmp_path, out_sustain_s=30.0)
    ctl._observe = lambda: _obs(mean_load=20.0)
    ctl.step()  # starts the sustain clock; no target move yet
    assert ctl.target() == 1
    # Spawn still fires below min? No: n_live==1 == target, no action.
    assert calls == []


def test_controller_cooldown_veto_emits_attributed_event(tmp_path):
    ctl, calls = _ctl(tmp_path, cooldown_s=3600.0)
    ctl._last_action = time.perf_counter() - 7200.0  # outside the window
    ctl._observe = lambda: _obs(mean_load=20.0)
    ctl.step()  # quiet long enough: the action is allowed
    assert ctl.target() == 2
    ctl._observe = lambda: _obs(mean_load=20.0, n_live=2, n_ready=2)
    ctl.step()  # _want just stamped _last_action: cooldown veto
    assert ctl.target() == 2
    snap = ctl.metrics.snapshot()
    assert snap.get("elastic_vetoes_total") == 1


def test_controller_flap_damper_vetoes(tmp_path):
    ctl, _ = _ctl(tmp_path, flap_max_actions=2)
    now = time.perf_counter()
    ctl._action_times = [now, now]
    ctl._observe = lambda: _obs(mean_load=20.0)
    ctl.step()
    assert ctl.target() == 1  # damped
    snap = ctl.metrics.snapshot()
    assert snap.get("elastic_vetoes_total") == 1


def test_controller_bounds_veto_at_max_and_min(tmp_path):
    ctl, calls = _ctl(tmp_path, max_backends=2)
    ctl._target = 2
    ctl._observe = lambda: _obs(mean_load=20.0, n_live=2, n_ready=2)
    ctl.step()
    assert ctl.target() == 2  # max_backends veto
    ctl._observe = lambda: _obs(mean_load=0.0, n_live=1)
    ctl._target = 1
    ctl.step()
    assert ctl.target() == 1  # min_backends veto
    assert ctl.metrics.snapshot().get("elastic_vetoes_total") == 2


def test_controller_replaces_dead_member_without_target_change(tmp_path):
    ctl, calls = _ctl(tmp_path)
    ctl._target = 2
    # Mid-load (between watermarks): no signal either way, but a member
    # died — capacity comes back without a target change.
    ctl._observe = lambda: _obs(mean_load=4.0, n_live=1)
    ctl.step()
    assert calls == [("spawn", "replace_dead")]
    assert ctl.target() == 2


def test_controller_scales_in_when_idle_sustained(tmp_path):
    ctl, calls = _ctl(tmp_path)
    ctl._target = 2
    ctl._observe = lambda: _obs(mean_load=0.2, n_live=2, n_ready=2)
    ctl.step()
    assert ctl.target() == 1
    assert calls == [("drain", "idle")]


def _live_ctl(tmp_path, **kw):
    """A controller with a REAL registry and no stubbing of _observe —
    for the observer-derived-liveness regressions."""
    defaults = dict(
        registry_path=str(tmp_path / "reg.json"),
        min_backends=1,
        max_backends=3,
        workdir=str(tmp_path),
    )
    defaults.update(kw)
    return ElasticController(
        ElasticConfig(**defaults), metrics=MetricsRegistry()
    )


def test_observe_excludes_unresponsive_registry_entries(tmp_path):
    # A kill -9'd / drained backend never unregisters; with no router
    # probing the registry, the controller itself must stop counting
    # it live once /statusz goes dark — or reconcile drains healthy
    # members against an inflated n_live (high-severity review fix).
    ctl = _live_ctl(tmp_path, statusz_miss_limit=2)
    ctl._registry.ensure(["http://127.0.0.1:1/", "http://127.0.0.1:2/"])
    stz = {"stats": {"queue_depth": 1}, "net": {"inflight": 0}}
    ctl._fetch_json = lambda url, timeout=1.0: (
        stz if url.startswith("http://127.0.0.1:1") else None
    )
    obs = ctl._observe()
    assert obs["n_live"] == 2  # one miss: transient-blip grace
    assert obs["n_ready"] == 1
    obs = ctl._observe()
    assert obs["n_live"] == 1  # miss streak hit the limit: it is gone
    # The dead entry recovering (respawn on the same URL) counts again.
    ctl._fetch_json = lambda url, timeout=1.0: stz
    obs = ctl._observe()
    assert obs["n_live"] == 2


def test_drain_and_reap_publish_registry_ejection(tmp_path):
    from distributedlpsolver_tpu.serve.elastic import ManagedBackend

    ctl = _live_ctl(tmp_path, drain_timeout_s=5.0)
    url = "http://127.0.0.1:3"
    ctl._registry.ensure([url])
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    mb = ManagedBackend(
        name="elastic-0-g1", slot=0, url=url, port=3, proc=proc,
        journal_dir=str(tmp_path), log_path=str(tmp_path / "x.log"),
        spawned_at=0.0, gen=1,
    )
    ctl._pool[mb.name] = mb
    ctl._drain_one(mb, reason="idle")
    entry = ctl._registry.load()["backends"][url]
    assert entry["ejected"] is True  # the stale entry cannot inflate n_live
    # Same for the reap path (kill -9 / OOM members).
    time.sleep(0.02)
    ctl._registry.record(url, ejected=False, fails=0,
                         observed_ts=time.time())
    ctl._pool[mb.name] = mb
    time.sleep(0.02)
    ctl._reap()
    assert ctl.pool_size() == 0
    assert ctl._registry.load()["backends"][url]["ejected"] is True


def test_observe_retains_reject_baseline_across_statusz_gap(tmp_path):
    # A transient /statusz miss must not reset the reject baseline:
    # rejects accrued during the gap still count toward the rate when
    # the backend reappears (low-severity review fix).
    ctl = _live_ctl(tmp_path, statusz_miss_limit=5)
    url = "http://127.0.0.1:4/"
    ctl._registry.ensure([url])

    def _stz(total):
        return {
            "stats": {
                "admission": {"t": {"rejected": {"queue_full": total}}}
            },
            "net": {"inflight": 0},
        }

    replies = iter([_stz(5), None, _stz(9)])
    ctl._fetch_json = lambda u, timeout=1.0: next(replies)
    ctl._observe()  # baseline: 5 rejects
    ctl._observe()  # blip: fetch fails, baseline must survive
    assert ctl._prev_rejects  # not wiped by the gap
    obs = ctl._observe()  # back: 9 - 5 = 4 rejects over the window
    assert obs["reject_rate"] > 0.0


def test_controller_rejects_inverted_bounds(tmp_path):
    with pytest.raises(ValueError):
        ElasticController(
            ElasticConfig(
                registry_path=str(tmp_path / "r.json"),
                min_backends=3,
                max_backends=1,
            ),
            metrics=MetricsRegistry(),
        )


# -- load ramp ---------------------------------------------------------------


def test_load_ramp_shape_and_gaps():
    from distributedlpsolver_tpu.net.chaos import LoadRamp

    ramp = LoadRamp(total=100, peak_rps=50.0, base_rps=5.0,
                    up_frac=0.3, down_frac=0.3)
    assert ramp.rps_at(0.0) == pytest.approx(5.0)
    assert ramp.rps_at(0.15) == pytest.approx(27.5)  # halfway up
    assert ramp.rps_at(0.3) == pytest.approx(50.0)
    assert ramp.rps_at(0.5) == pytest.approx(50.0)  # the hold plateau
    assert ramp.rps_at(0.7) == pytest.approx(50.0)
    assert ramp.rps_at(1.0) == pytest.approx(5.0)
    # Gaps are the pacing reciprocal: tight at the peak, wide at the
    # edges, and always positive.
    gaps = [ramp.gap_s(i) for i in range(100)]
    assert all(g > 0 for g in gaps)
    assert min(gaps) == pytest.approx(1.0 / 50.0)
    assert gaps[0] == pytest.approx(1.0 / 5.0)
    with pytest.raises(ValueError):
        LoadRamp(total=0, peak_rps=10.0)


# -- scale-in under load: drain resolves outstanding async polls -------------


def _post_json(url, body, timeout=30.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get_json(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}
    except Exception as e:
        return 599, {"error": str(e)}


def test_scale_in_under_load_resolves_outstanding_async_polls(tmp_path):
    """Satellite: drain a pool member that still owes async verdicts.
    Every outstanding poll resolves through the router's fan-out: the
    victim answers while it drains, and any poll that misses that
    window re-binds in the successor the controller spawns on the same
    slot (the reused journal dir serves the stored results). The
    scale_in action records drained=True and the slot's journal shows
    zero duplicate solves across both incarnations."""
    from distributedlpsolver_tpu.net.chaos import (
        ChaosPlane,
        journal_duplicate_solves,
    )

    workdir = str(tmp_path)
    registry_path = os.path.join(workdir, "registry.json")
    plane = ChaosPlane(workdir)
    ctl = ElasticController(
        ElasticConfig(
            registry_path=registry_path,
            min_backends=2,
            max_backends=2,
            workdir=workdir,
            backend_flags=("--flush-ms", "20", "--batch", "4",
                           "--queue-depth", "128", "--quiet"),
            heartbeat_s=0.25,
        ),
        metrics=MetricsRegistry(),
    )
    try:
        ctl.step()  # one spawn per reconcile cycle
        ctl.step()
        assert ctl.pool_size() == 2, "min pool did not come up"
        router = plane.spawn_router("router-1", [], registry_path)
        assert plane.wait_ready(router, 60), "router did not come up"
        pool = ctl.statusz()["pool"]
        victim = next(m for m in ctl._pool.values() if m.url == pool[1]["url"])

        # Load the victim with async work, directly (so we KNOW which
        # backend owes the verdicts).
        ids = []
        for k in range(16):
            code, out = _post_json(
                victim.url + "/v1/solve",
                {"m": 8, "n": 24, "seed": k, "tenant": "t",
                 "async": True, "id": f"drain-{k}"},
                timeout=30.0,
            )
            assert code == 202 and out.get("id"), (code, out)
            ids.append(out["id"])

        # Outstanding polls run THROUGH THE ROUTER while the drain is
        # in progress — the fan-out reaches the draining backend.
        verdicts = {}

        def poll(rid):
            # 404 is transient during the handoff: the victim's
            # listener closed but its successor (same slot, same
            # journal) has not registered yet — keep polling.
            deadline = time.perf_counter() + 240.0
            while time.perf_counter() < deadline:
                c, o = _get_json(router.url + f"/v1/solve/{rid}")
                if c in (202, 404, 502, 503, 599):
                    time.sleep(0.05)
                    continue
                verdicts[rid] = (c, o.get("status"))
                return
            verdicts[rid] = (None, None)

        pollers = [
            threading.Thread(target=poll, args=(rid,), daemon=True)
            for rid in ids
        ]
        for t in pollers:
            t.start()
        ctl._drain_one(victim, reason="test")  # blocks until drained
        act = next(
            a for a in ctl.actions() if a["event"] == "scale_in"
        )
        assert act["drained"] is True and act["backend"] == victim.url
        assert ctl.pool_size() == 1
        # Reconcile back toward the target: the successor lands on the
        # freed slot once the routers eject the dead listener from the
        # registry, and re-binds the drained incarnation's poll ids.
        deadline = time.perf_counter() + 180.0
        while ctl.pool_size() < 2 and time.perf_counter() < deadline:
            ctl.step()
            time.sleep(0.5)
        assert ctl.pool_size() == 2, "successor never spawned"
        for t in pollers:
            t.join(timeout=300)

        bad = {r: v for r, v in verdicts.items() if v != (200, "optimal")}
        assert not bad, f"polls lost across the drain: {bad}"
        assert len(verdicts) == len(ids)
        assert journal_duplicate_solves(victim.journal_dir) == 0
    finally:
        ctl.shutdown(drain=False)
        plane.shutdown_all()


# -- slow-tier smoke: the chaos-elasticity acceptance run --------------------


@pytest.mark.slow
def test_probe_elastic_serve_smoke():
    """CI satellite: the chaos-elasticity acceptance probe — a load
    ramp over a live plane (router + controller-owned pool), one pool
    member SIGKILLed mid-scale, brownout engage/release, scale back in
    via drain — runs under a wall budget (slow tier: the ramp +
    compile-heavy pool respawns cost ~2 min of 1-core wall; the
    controller/ladder/breaker/drain tests above stay in tier-1),
    asserting zero lost acks, zero duplicate solves, and zero warm
    recompiles at steady state."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "probe_elastic_serve.py"),
         "--requests", "240", "--budget-s", "300"],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    tail = "\n".join(proc.stdout.splitlines()[-40:])
    assert proc.returncode == 0, (
        f"probe_elastic_serve failed (rc={proc.returncode}):\n{tail}\n"
        f"stderr:\n{proc.stderr[-2000:]}"
    )
    assert "PASS" in proc.stdout
