"""Data-parallel, pipelined serve dispatch tests: mesh-sharded bucket
solves (equivalence + divisibility + zero-warm-recompile per mesh),
pipeline ordering (batch k results never wait on batch k+1's pack),
ladder autotuning (split/merge/cap + the online drain→swap→warm epoch),
and elastic mesh shrink mid-service — all on the 8-virtual-CPU-device
rig."""

import json
import time

import jax
import numpy as np
import pytest

from distributedlpsolver_tpu.backends.batched import (
    bucket_cache_size,
    place_bucket,
    solve_bucket,
)
from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.generators import (
    random_batched_lp,
    random_request_stream,
)
from distributedlpsolver_tpu.parallel import make_mesh
from distributedlpsolver_tpu.serve import (
    AutotuneConfig,
    BucketSpec,
    BucketTable,
    ServiceConfig,
    SolveService,
    autotune_ladder,
    ladder_from_json,
    ladder_to_json,
)
from distributedlpsolver_tpu.serve.autotune import load_request_shapes

pytestmark = pytest.mark.serve


def _batch_mesh(k: int):
    return make_mesh((k,), axis_names=("batch",), devices=jax.devices()[:k])


class TestMeshBucketDispatch:
    def test_sharded_matches_unsharded_to_1e8(self):
        """ISSUE acceptance: sharded bucket results match unsharded to
        1e-8 on the tier-1 CPU mesh (they are the same compiled math —
        placement only — so the agreement is near-bitwise)."""
        batch = random_batched_lp(8, 10, 30, seed=11)
        active = np.array([True] * 6 + [False] * 2)
        r0 = solve_bucket(batch, active)
        r1 = solve_bucket(batch, active, mesh=_batch_mesh(4))
        for k in range(6):
            assert r1.status[k] == r0.status[k] == Status.OPTIMAL
        np.testing.assert_allclose(r1.x[:6], r0.x[:6], atol=1e-8, rtol=1e-8)
        np.testing.assert_allclose(
            r1.objective[:6], r0.objective[:6], atol=1e-8, rtol=1e-8
        )

    def test_batch_not_divisible_by_mesh_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            solve_bucket(
                random_batched_lp(6, 8, 24, seed=1),
                np.ones(6, bool),
                mesh=_batch_mesh(4),
            )

    def test_preplaced_bucket_reuses_program(self):
        """place_bucket (the pack stage) + solve_bucket must land on the
        same compiled program as the direct call — the pipeline cannot
        fork the cache."""
        mesh = _batch_mesh(2)
        batch = random_batched_lp(8, 8, 24, seed=2)
        active = np.ones(8, bool)
        solve_bucket(batch, active, mesh=mesh)  # compile
        size0 = bucket_cache_size()
        placed, act = place_bucket(batch, active, mesh=mesh)
        r = solve_bucket(placed, act, mesh=mesh)
        assert bucket_cache_size() == size0
        assert r.n_optimal == 8

    def test_bucket_table_enforces_device_divisibility(self):
        # auto batch rounds up to a devices multiple
        t = BucketTable(batch=6, devices=4)
        assert t.batch == 8
        assert t.spec_for(8, 24).batch == 8
        # explicit non-divisible buckets are a loud config error
        with pytest.raises(ValueError, match="divisible"):
            BucketTable([BucketSpec(8, 32, 6)], devices=4)


class TestPipeline:
    def test_batch_k_results_never_wait_on_pack_k1(self):
        """ISSUE acceptance: with the two-deep pipeline, batch k's
        futures resolve while batch k+1 is still packing — a slow pack
        must never serialize behind-the-device work."""
        shape = ((8, 24),)  # one shape → one bucket → deterministic batches
        svc = SolveService(ServiceConfig(batch=4, flush_s=0.01))
        try:
            # Warm the bucket so solve time is not compile-dominated.
            warm = [
                svc.submit(p)
                for p in random_request_stream(4, shapes=shape, seed=1)
            ]
            assert svc.drain(timeout=300)
            for f in warm:
                assert f.result(timeout=30).status is Status.OPTIMAL

            orig = svc._pack_bucket
            packs = []

            def slow_pack(key, live):
                if packs:  # pack of every batch after the first is slow
                    time.sleep(1.0)
                out = orig(key, live)
                packs.append(time.perf_counter())
                return out

            svc._pack_bucket = slow_pack
            futs = [
                svc.submit(p)
                for p in random_request_stream(8, shapes=shape, seed=2)
            ]
            assert svc.drain(timeout=300)
            rs = [f.result(timeout=30) for f in futs]
            assert all(r.status is Status.OPTIMAL for r in rs)
            assert len(packs) >= 2
            batch1 = [r for r in rs if r.dispatch_index == rs[0].dispatch_index]
            assert len(batch1) == 4
            # batch 1 completed before batch 2's (artificially slow) pack
            # finished — its results never waited on the next pack.
            assert max(r.t_done for r in batch1) < packs[1]
        finally:
            svc.shutdown()

    def test_dispatch_report_records_stage_split(self):
        svc = SolveService(ServiceConfig(batch=4, flush_s=0.01))
        try:
            futs = [svc.submit(p) for p in random_request_stream(8, seed=3)]
            assert svc.drain(timeout=300)
            rs = [f.result(timeout=30) for f in futs]
            report = svc.dispatch_report()
            assert report, "bucket dispatches must produce timing rows"
            for row in report:
                for field in (
                    "pack_ms", "compile_ms", "solve_ms", "overlap_ms",
                    "mesh_devices",
                ):
                    assert field in row
                assert row["pack_ms"] > 0 and row["solve_ms"] > 0
            # the same split is stamped on every bucketed request record
            assert all(r.pack_ms > 0 for r in rs if r.bucket)
            stats = svc.stats()
            assert stats["pack_ms_total"] > 0
            assert "idle" in stats and stats["idle"]["waits"] >= 0
        finally:
            svc.shutdown()

    def test_drain_is_event_driven(self):
        """drain() must return promptly once the last result lands (no
        fixed poll tick) and report False on timeout while work remains."""
        svc = SolveService(ServiceConfig(batch=4, flush_s=0.01))
        try:
            fut = svc.submit(next(random_request_stream(1, seed=9)))
            # immediately-expiring drain on a busy service: False, fast
            t0 = time.perf_counter()
            assert svc.drain(timeout=0.001) in (False, True)
            assert svc.drain(timeout=300)
            assert fut.result(timeout=30).status is Status.OPTIMAL
        finally:
            svc.shutdown()


class TestAutotune:
    def test_split_hot_merge_cold_cap_programs(self):
        # 90% of traffic at (10, 48): its pow2 bucket (16, 64) wastes
        # >50% of every A-cell — the autotuner must give it a tighter
        # bucket; the 2% tail shape merges away; the cap holds.
        shapes = [(10, 48)] * 90 + [(30, 100)] * 8 + [(5, 9)] * 2
        current = [BucketSpec(16, 64, 8), BucketSpec(32, 128, 8)]
        specs, report = autotune_ladder(
            shapes,
            current=current,
            config=AutotuneConfig(max_programs=2, devices=2, batch=8),
        )
        assert 1 <= len(specs) <= 2
        table = BucketTable(specs, devices=2)
        for m, n in {(10, 48), (30, 100), (5, 9)}:
            s = table.spec_for(m, n)  # every observed shape still fits
            assert s.batch % 2 == 0
        hot = table.spec_for(10, 48)
        assert hot.m * hot.n < 16 * 64  # strictly tighter than the pow2 bucket
        assert report["mean_shape_waste_after"] < report["mean_shape_waste_before"]
        assert report["split_buckets"], "the wasteful hot bucket is reported"

    def test_empty_telemetry_keeps_ladder(self):
        current = [BucketSpec(16, 64, 8)]
        specs, report = autotune_ladder([], current=current)
        assert specs == current
        assert report["requests"] == 0

    def test_ladder_json_roundtrip(self):
        specs = [BucketSpec(16, 56, 8), BucketSpec(32, 104, 8)]
        assert ladder_from_json(ladder_to_json(specs)) == specs
        # bare-triple form parses too
        assert ladder_from_json("[[16, 56, 8]]") == [BucketSpec(16, 56, 8)]

    def test_load_request_shapes_skips_solo_and_junk(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text(
            json.dumps({"event": "request", "bucket": [16, 32, 8],
                        "m": 9, "n": 25}) + "\n"
            + json.dumps({"event": "request", "bucket": None,
                          "m": 6, "n": 10}) + "\n"  # solo path: skipped
            + json.dumps({"event": "batch"}) + "\n"
            + "not json\n"
        )
        assert load_request_shapes(str(p)) == [(9, 25)]


class TestServiceIntegration:
    def test_mesh_dispatch_autotune_swap_zero_warm_recompiles_200(
        self, tmp_path
    ):
        """ISSUE acceptance: bucket_cache_size() stays flat across a warm
        200-request run under BOTH mesh-sharded dispatch and a
        post-autotune ladder, and the service answers match reference
        single-solves at 1e-8."""
        log = tmp_path / "svc.jsonl"
        cfg = ServiceConfig(
            batch=8, flush_s=0.02, mesh_devices=2, log_jsonl=str(log)
        )
        with SolveService(cfg) as svc:
            assert svc.mesh_devices == 2
            # Cold wave: builds the telemetry the autotuner folds back in.
            cold = [svc.submit(p) for p in random_request_stream(48, seed=31)]
            assert svc.drain(timeout=600)
            for f in cold:
                assert f.result(timeout=30).status is Status.OPTIMAL

            specs, report = autotune_ladder(
                load_request_shapes(str(log)),
                current=list(svc.scheduler.table.specs()),
                config=AutotuneConfig(devices=2, batch=8),
            )
            assert (
                report["mean_shape_waste_after"]
                <= report["mean_shape_waste_before"]
            )
            # Online swap at the epoch boundary: drain → swap → warm.
            warmed = svc.apply_ladder(specs)
            assert warmed == len(specs)

            # Warm 200-request run on the new ladder over the mesh:
            # zero recompiles, all optimal, no compile_ms on any record.
            cache0 = bucket_cache_size()
            problems = list(random_request_stream(200, seed=32))
            futs = [svc.submit(p) for p in problems]
            assert svc.drain(timeout=600)
            rs = [f.result(timeout=30) for f in futs]
            assert bucket_cache_size() == cache0
            assert all(r.status is Status.OPTIMAL for r in rs)
            assert all(r.compile_ms == 0.0 for r in rs)
            # the refined ladder actually serves (bucketed, not solo)
            assert all(r.bucket is not None for r in rs)

            # sharded-dispatch answers agree with solo reference solves
            for p, r in list(zip(problems, rs))[:8]:
                ref = solve(p, backend="tpu")
                assert ref.status == Status.OPTIMAL
                assert abs(r.objective - ref.objective) <= 1e-8 * (
                    1.0 + abs(ref.objective)
                )

            events = [
                json.loads(l) for l in log.read_text().splitlines()
            ]
            assert any(e["event"] == "ladder_swap" for e in events)
            assert any(e["event"] == "warmup" for e in events)

    def test_reshard_mid_service_keeps_serving(self):
        """Elastic recovery under the service: losing a mesh device
        re-forms the batch mesh over survivors (clamped so bucket batches
        stay divisible) and dispatch continues; the re-formed mesh pays
        one compile per bucket (per-(bucket, mesh) invariant), then stays
        warm."""
        cfg = ServiceConfig(batch=8, flush_s=0.02, mesh_devices=4)
        with SolveService(cfg) as svc:
            futs = [svc.submit(p) for p in random_request_stream(16, seed=41)]
            assert svc.drain(timeout=600)
            for f in futs:
                assert f.result(timeout=30).status is Status.OPTIMAL
            # lose one of the 4 devices: 3 survivors, clamped to 2 so the
            # batch-of-8 buckets stay shardable
            assert svc.reshard(exclude=[jax.devices()[3]]) == 2
            assert svc.mesh_devices == 2
            futs = [svc.submit(p) for p in random_request_stream(16, seed=42)]
            assert svc.drain(timeout=600)
            rs = [f.result(timeout=30) for f in futs]
            assert all(r.status is Status.OPTIMAL for r in rs)
            # warm again on the new mesh: no further compiles
            cache0 = bucket_cache_size()
            futs = [svc.submit(p) for p in random_request_stream(8, seed=43)]
            assert svc.drain(timeout=600)
            assert all(
                f.result(timeout=30).status is Status.OPTIMAL for f in futs
            )
            assert bucket_cache_size() == cache0


def test_cli_jax_cache_dir_logs_hit_miss_line(tmp_path, capsys):
    """Satellite: --jax-cache-dir points the persistent compilation cache
    somewhere explicit and logs the cold/warm line at startup."""
    from distributedlpsolver_tpu.cli import main

    req = tmp_path / "req.jsonl"
    req.write_text(json.dumps({"m": 8, "n": 24, "seed": 0, "id": "q0"}) + "\n")
    out = tmp_path / "res.jsonl"
    cache = tmp_path / "xla-cache"
    rc = main(
        [
            "serve", "--requests", str(req), "--out", str(out),
            "--batch", "4", "--flush-ms", "5",
            "--jax-cache-dir", str(cache),
        ]
    )
    assert rc == 0
    err = capsys.readouterr().err
    assert "jax compilation cache" in err and "cold start" in err
    assert cache.exists()


def test_cli_autotune_roundtrip(tmp_path):
    """cli autotune consumes a telemetry stream and writes a ladder file
    cli serve --buckets accepts."""
    from distributedlpsolver_tpu.cli import main

    telem = tmp_path / "telemetry.jsonl"
    telem.write_text(
        "".join(
            json.dumps(
                {"event": "request", "bucket": [16, 64, 8], "m": 10, "n": 48}
            )
            + "\n"
            for _ in range(20)
        )
    )
    ladder = tmp_path / "ladder.json"
    rc = main(
        [
            "autotune", "--telemetry", str(telem), "--out", str(ladder),
            "--batch", "8", "--devices", "2",
        ]
    )
    assert rc == 0
    specs = ladder_from_json(ladder.read_text())
    assert specs and all(s.batch % 2 == 0 for s in specs)
    t = BucketTable(specs, devices=2)
    assert t.spec_for(10, 48).m * t.spec_for(10, 48).n < 16 * 64
