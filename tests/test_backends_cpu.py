"""CPU + native-kernel backend tests (SURVEY.md §2.1) and CLI smoke tests.

The CPU path is an independent execution engine for the shared IPM core
(numpy eager vs jitted XLA), so agreement between 'cpu', 'cpu-native',
and 'tpu' is a strong cross-check of all three.
"""

import json

import numpy as np
import pytest

from distributedlpsolver_tpu import cli
from distributedlpsolver_tpu.io.mps import write_mps
from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.generators import random_dense_lp, random_general_lp
from tests.oracle import highs_on_general

try:
    from distributedlpsolver_tpu.native import available as _native_available

    HAVE_NATIVE = _native_available()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not HAVE_NATIVE, reason="g++ unavailable")


@pytest.mark.parametrize("backend", ["cpu", pytest.param("cpu-native", marks=needs_native)])
def test_cpu_backends_match_highs(backend):
    p = random_general_lp(25, 45, seed=4)
    r = solve(p, backend=backend, max_iter=60)
    hi = highs_on_general(p)
    assert r.status == Status.OPTIMAL
    assert abs(r.objective - hi.fun) <= 2e-6 * (1 + abs(hi.fun))


@needs_native
def test_native_agrees_with_numpy_cpu():
    p = random_dense_lp(35, 80, seed=9)
    r1 = solve(p, backend="cpu", max_iter=60)
    r2 = solve(p, backend="cpu-native", max_iter=60)
    assert r1.status == r2.status == Status.OPTIMAL
    # identical algorithm, different kernels: same iterate path to rounding
    assert r1.iterations == r2.iterations
    assert r2.objective == pytest.approx(r1.objective, rel=1e-9)


@needs_native
def test_native_kernels_against_numpy_oracle(rng):
    """Kernel-level unit tests: AD²Aᵀ assembly and Cholesky solve vs
    NumPy/SciPy (SURVEY.md §4 'kernel tests ... vs NumPy oracle')."""
    import ctypes

    from distributedlpsolver_tpu.native import load

    lib = load()
    m, n = 17, 29
    A = np.ascontiguousarray(rng.standard_normal((m, n)))
    d = np.ascontiguousarray(rng.uniform(0.5, 2.0, n))
    M = np.empty((m, m))
    scratch = np.empty((m, n))
    dp = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    lib.dlps_normal_eq(dp(A), dp(d), m, n, 0.0, dp(scratch), dp(M))
    np.testing.assert_allclose(M, (A * d) @ A.T, rtol=1e-12, atol=1e-12)

    Mreg = M + np.eye(m) * 1e-6
    L = np.ascontiguousarray(Mreg.copy())
    info = lib.dlps_cholesky(dp(L), m)
    assert info == 0
    rhs = np.ascontiguousarray(rng.standard_normal(m))
    out = np.empty(m)
    lib.dlps_cho_solve(dp(L), dp(rhs), m, dp(out))
    np.testing.assert_allclose(out, np.linalg.solve(Mreg, rhs), rtol=1e-9, atol=1e-10)

    # non-PD must be reported, not crash
    bad = np.ascontiguousarray(-np.eye(m))
    assert lib.dlps_cholesky(dp(bad), m) == 1


def test_cli_solve_json(tmp_path, capsys):
    p = random_general_lp(15, 25, seed=6)
    f = str(tmp_path / "p.mps")
    write_mps(p, f)
    rc = cli.main(["solve", f, "--backend", "cpu", "--quiet", "--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    assert rc == 0
    assert rec["status"] == "optimal"
    hi = highs_on_general(p)
    assert abs(rec["objective"] - hi.fun) <= 2e-6 * (1 + abs(hi.fun))


def test_cli_generate_and_backends(tmp_path, capsys):
    f = str(tmp_path / "g.mps")
    rc = cli.main(["generate", "block", f, "--m", "10", "--n", "20", "--blocks", "2", "--link", "4"])
    assert rc == 0
    rc = cli.main(["backends"])
    assert rc == 0
    out = capsys.readouterr().out
    for name in ["cpu", "tpu", "sharded", "cpu-native"]:
        assert name in out


def test_cli_x_out_roundtrip(tmp_path, capsys):
    p = random_dense_lp(12, 25, seed=8)
    f = str(tmp_path / "p.mps")
    xf = str(tmp_path / "x.npy")
    write_mps(p, f)
    rc = cli.main(["solve", f, "--backend", "cpu", "--quiet", "--x-out", xf])
    assert rc == 0
    x = np.load(xf)
    assert p.max_violation(x) <= 1e-6 * (1 + float(np.abs(x).max()))


def test_auto_backend_picks_by_size_and_structure():
    # On the CPU test platform auto always resolves to cpu-native; the
    # selection rules themselves are checked directly against both
    # platforms.
    import jax

    from distributedlpsolver_tpu.backends.auto import choose_backend_name
    from distributedlpsolver_tpu.ipm import solve
    from distributedlpsolver_tpu.ipm.state import Status
    from distributedlpsolver_tpu.models.generators import (
        block_angular_lp,
        random_dense_lp,
        random_general_lp,
    )
    from distributedlpsolver_tpu.models.problem import to_interior_form

    tiny = to_interior_form(random_general_lp(27, 51, seed=0))
    big = to_interior_form(random_dense_lp(600, 1200, seed=0))
    blocky = to_interior_form(block_angular_lp(8, 96, 256, 64, seed=0, sparse=False))
    assert choose_backend_name(tiny, "tpu") == ("cpu-native", None)
    assert choose_backend_name(big, "tpu") == ("tpu", None)
    assert choose_backend_name(blocky, "tpu") == ("block", None)
    assert choose_backend_name(big, "cpu") == ("cpu-native", None)

    r = solve(random_general_lp(12, 30, seed=4), backend="auto")
    assert r.status == Status.OPTIMAL
    assert r.backend == "auto(cpu-native)"
