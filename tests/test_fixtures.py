"""Golden MPS fixtures: exact parse checks + hand-derived optima.

VERDICT.md round 1 item 7: real Netlib files are unreachable (zero
egress), so these vendored hand-written files carry the real-world
quirks instead — RANGES on all three row types (incl. negative range on
an E row), the negative-UP lower-bound quirk, MI/FX bounds, extra free N
rows, objective-row RHS constants, duplicate COLUMNS entries, OBJSENSE
section-body form — with optima derived BY HAND (independent of any
solver), plus a ≥10 MB file emitted by an independent writer (not
io/mps.py's) for parser performance and cross-writer compatibility.
"""

import io
import os
import time

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.io import read_mps
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.ipm.state import Status

from tests.oracle import highs_on_general

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class TestQuirksFixture:
    """quirks.mps — feasible set derivation (all by hand):

    rows   LIM1 (L, rhs 4, range 4)   → X1+X2 ∈ [0, 4]
           LIM2 (G, rhs 0, range 3)   → X1+X4 ∈ [0, 3]
           EQ1  (E, rhs 2, range 1.5) → X2+X3 ∈ [2, 3.5]
           EQ2  (E, rhs 3, range -1)  → X3+X4 ∈ [2, 3]
    bounds X1 ≤ -1 (UP −1 on default lb ⇒ lb −∞), X2 ∈ (−∞, 5],
           X3 ≥ 0, X4 = 1.5 (FX)
    obj    min X1 + 2·X2 + X3 + 10   (X3's two 0.5 entries sum; RHS −10
           on COST ⇒ constant +10)

    X4 = 1.5 ⇒ X3 ∈ [0.5, 1.5]; LIM2 ⇒ X1 ∈ [−1.5, −1]. On LIM1's lower
    face X1 = −X2 the objective is X2 + X3 + 10 ≥ EQ1's lower bound 2
    + 10 = 12, attained along the segment X2 = 2 − X3, X2 ∈ [1, 1.5]
    (X1 = −X2, X3 = 2 − X2). The VALUE 12.0 is unique; the optimal set
    is that segment — HiGHS returns the vertex X2 = 1.5, an IPM returns
    the segment's analytic center, so only the vertex oracle asserts x.
    """

    OPT = 12.0
    X_OPT = np.array([-1.5, 1.5, 0.5, 1.5])  # the HiGHS vertex

    def parse(self):
        return read_mps(os.path.join(FIXTURES, "quirks.mps"))

    def test_exact_parse(self):
        p = self.parse()
        assert p.name == "QUIRKS"
        assert p.row_names == ["LIM1", "LIM2", "EQ1", "EQ2"]  # FREEROW dropped
        assert p.col_names == ["X1", "X2", "X3", "X4"]
        np.testing.assert_allclose(p.c, [1.0, 2.0, 1.0, 0.0])  # 0.5+0.5 summed
        assert p.c0 == 10.0
        A = np.asarray(p.A.todense() if sp.issparse(p.A) else p.A)
        np.testing.assert_allclose(
            A,
            [[1, 1, 0, 0],
             [1, 0, 0, 1],
             [0, 1, 1, 0],
             [0, 0, 1, 1]],
        )
        np.testing.assert_allclose(p.rlb, [0.0, 0.0, 2.0, 2.0])
        np.testing.assert_allclose(p.rub, [4.0, 3.0, 3.5, 3.0])
        np.testing.assert_allclose(p.lb, [-np.inf, -np.inf, 0.0, 1.5])
        np.testing.assert_allclose(p.ub, [-1.0, 5.0, np.inf, 1.5])
        assert not p.maximize

    def test_highs_agrees_with_hand_optimum(self):
        p = self.parse()
        ref = highs_on_general(p)  # oracle solves min cᵀx without c0
        assert ref.fun + p.c0 == pytest.approx(self.OPT, abs=1e-8)
        np.testing.assert_allclose(ref.x, self.X_OPT, atol=1e-8)

    def test_solver_reaches_hand_optimum(self):
        p = self.parse()
        r = solve(p, backend="cpu")
        assert r.status == Status.OPTIMAL
        assert r.objective == pytest.approx(self.OPT, abs=1e-6)
        # Any point of the optimal segment is acceptable: x lies on it iff
        # x1 = -x2, x3 = 2 - x2, x2 ∈ [1, 1.5], x4 = 1.5.
        x = r.x
        assert x[0] == pytest.approx(-x[1], abs=1e-5)
        assert x[2] == pytest.approx(2.0 - x[1], abs=1e-5)
        assert 1.0 - 1e-5 <= x[1] <= 1.5 + 1e-5
        assert x[3] == pytest.approx(1.5, abs=1e-7)


class TestMaximizeFixture:
    """maximize.mps — max 3A+5B, 2A+4B ≤ 10, A ≥ −2, A∈[0,3], B∈[0,2].

    A yields 1.5/unit-capacity vs B's 1.25 ⇒ saturate A = 3 (capacity 6),
    B = (10−6)/4 = 1. Optimum 3·3 + 5·1 = 14.0.
    """

    OPT = 14.0

    def test_parse_and_optima(self):
        p = read_mps(os.path.join(FIXTURES, "maximize.mps"))
        assert p.maximize
        np.testing.assert_allclose(p.rlb, [-np.inf, -2.0])
        np.testing.assert_allclose(p.rub, [10.0, np.inf])
        ref = highs_on_general(p)  # minimized internal form
        assert -ref.fun == pytest.approx(self.OPT, abs=1e-8)
        r = solve(p, backend="cpu")
        assert r.status == Status.OPTIMAL
        assert r.objective == pytest.approx(self.OPT, abs=1e-6)
        np.testing.assert_allclose(r.x, [3.0, 1.0], atol=1e-5)


def _emit_big_mps(fh, m_blocks: int, rows_per: int, cols_per: int, seed: int):
    """An INDEPENDENT MPS emitter (deliberately not io/mps.write_mps):
    fixed-format-ish columns, varying pair counts per line, interleaved
    comments, tab separators, and an RHS set name — the formatting
    variety a parser meets in the wild."""
    rng = np.random.default_rng(seed)
    fh.write("* big generated instance\nNAME BIGGEN\nROWS\n N  obj\n")
    for b in range(m_blocks):
        for i in range(rows_per):
            fh.write(f" {'LG'[i % 2]}  r{b}_{i}\n")
    fh.write("COLUMNS\n")
    for b in range(m_blocks):
        if b % 7 == 0:
            fh.write(f"* block {b}\n")
        for j in range(cols_per):
            name = f"x{b}_{j}"
            fh.write(f"    {name}\tobj\t{rng.uniform(0.5, 2.0):.6f}\n")
            # two constraint entries, sometimes paired on one line
            i1, i2 = rng.integers(0, rows_per, size=2)
            v1, v2 = rng.uniform(-2, 2, size=2)
            if j % 3 == 0:
                fh.write(f"    {name}  r{b}_{i1}  {v1:.6f}  r{b}_{i2}  {v2:.6f}\n")
            else:
                fh.write(f"    {name}  r{b}_{i1}  {v1:.6f}\n")
                fh.write(f"    {name}  r{b}_{i2}  {v2:.6f}\n")
    fh.write("RHS\n")
    for b in range(m_blocks):
        for i in range(rows_per):
            fh.write(f"    rhs\tr{b}_{i}\t{rng.uniform(1.0, 5.0):.6f}\n")
    fh.write("BOUNDS\n")
    for b in range(0, m_blocks, 3):
        fh.write(f" UP bnd  x{b}_0  {rng.uniform(3.0, 9.0):.6f}\n")
    fh.write("ENDATA\n")


def test_large_file_parse_performance(tmp_path):
    # ≥10 MB emitted by the independent writer above; the parser must get
    # through it in well under a minute and land exact dimensions.
    path = tmp_path / "big.mps"
    m_blocks, rows_per, cols_per = 560, 40, 220
    with open(path, "w") as fh:
        _emit_big_mps(fh, m_blocks, rows_per, cols_per, seed=0)
    size = os.path.getsize(path)
    assert size >= 10 * 1024 * 1024, f"fixture too small: {size} bytes"
    t0 = time.perf_counter()
    p = read_mps(path)
    dt = time.perf_counter() - t0
    assert p.shape == (m_blocks * rows_per, m_blocks * cols_per)
    assert sp.issparse(p.A)
    assert p.A.nnz > 0
    assert dt < 60.0, f"parse took {dt:.1f}s"


def test_independent_writer_round_trips_through_ours(tmp_path):
    # Parse an independently-emitted small instance, write it with OUR
    # writer, re-read, and require identical problem data.
    buf = io.StringIO()
    _emit_big_mps(buf, 2, 8, 12, seed=7)
    buf.seek(0)
    from distributedlpsolver_tpu.io import read_mps as _read
    from distributedlpsolver_tpu.io import write_mps

    p1 = _read(buf)
    path = tmp_path / "rt.mps"
    write_mps(p1, path)
    p2 = _read(path)
    np.testing.assert_allclose(p1.c, p2.c)
    A1 = np.asarray(p1.A.todense() if sp.issparse(p1.A) else p1.A)
    A2 = np.asarray(p2.A.todense() if sp.issparse(p2.A) else p2.A)
    np.testing.assert_allclose(A1, A2)
    np.testing.assert_allclose(p1.rlb, p2.rlb)
    np.testing.assert_allclose(p1.rub, p2.rub)
    np.testing.assert_allclose(p1.lb, p2.lb)
    np.testing.assert_allclose(p1.ub, p2.ub)
