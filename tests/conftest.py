"""Test configuration.

Tests run on CPU with 8 virtual XLA host devices so the mesh/psum sharded
code paths execute without TPU hardware (SURVEY.md §4: the analogue of the
reference's `mpirun -np N` single-machine multi-rank testing). Must run
before jax initializes, hence module level in conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize registers the TPU plugin and pins the platform
# programmatically, which overrides the env var — force CPU the same way.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
