"""The df32 mixed-precision bucket engine on the serve hot path
(ISSUE 7 acceptance, CPU tier-1):

* df32-scheduled bucket solves match the all-f64 path to 1e-8 on the
  200-request probe shapes,
* results are bitwise-stable across dispatches,
* the zero-warm-recompile invariant holds (bucket_cache_size unchanged
  across repeat dispatches and across a 200-request service run),
* fused-k iteration fusion is bitwise-equivalent to k = 1,
* the segmented dispatch path donates its carry (no aliasing copy,
  asserted via the compiled program's memory analysis where available),
* the service stamps schedule / fused-iters telemetry and warm_buckets
  logs a compile-cache hit/miss line per bucket.
"""

import json

import numpy as np
import pytest

pytest.importorskip("jax")

from distributedlpsolver_tpu.backends.batched import (
    bucket_cache_size,
    bucket_donation_report,
    solve_bucket,
)
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import Status
from distributedlpsolver_tpu.models.generators import (
    random_batched_lp,
    random_request_stream,
)
from distributedlpsolver_tpu.serve import ServiceConfig, SolveService
from distributedlpsolver_tpu.serve.buckets import (
    BucketSpec,
    BucketTable,
    pad_standard_form,
)
from distributedlpsolver_tpu.serve.service import standard_form

pytestmark = pytest.mark.serve

_DF32 = SolverConfig(bucket_schedule="df32")
_F64 = SolverConfig(bucket_schedule="f64")


def _probe_buckets(n_requests=200, batch=8, seed=13):
    """The 200-request probe stream bucketed exactly as the service
    would: one padded (B, m, n) batch per distinct bucket shape, filled
    with the stream's own problems."""
    table = BucketTable(batch=batch)
    per_bucket = {}
    for p in random_request_stream(n_requests, seed=seed):
        c, A, b = standard_form(p)
        spec = table.spec_for(*A.shape)
        per_bucket.setdefault(spec.key(), (spec, []))[1].append((c, A, b))
    out = []
    for spec, members in per_bucket.values():
        B = spec.batch
        A = np.zeros((B, spec.m, spec.n))
        b = np.zeros((B, spec.m))
        c = np.zeros((B, spec.n))
        active = np.zeros(B, dtype=bool)
        for k, (cc, AA, bb) in enumerate(members[:B]):
            c[k], A[k], b[k] = pad_standard_form(cc, AA, bb, spec.m, spec.n)
            active[k] = True
        for k in range(int(active.sum()), B):
            A[k], b[k], c[k] = A[0], b[0], c[0]
        from distributedlpsolver_tpu.models.generators import BatchedLP

        out.append((spec, BatchedLP(c=c, A=A, b=b, name="probe"), active))
    return out


class TestScheduleEquivalence:
    def test_df32_matches_f64_on_probe_shapes(self):
        """Acceptance: every active member of every probe-shape bucket is
        OPTIMAL under the df32 schedule and agrees with the all-f64
        reference to 1e-8 relative."""
        buckets = _probe_buckets()
        assert len(buckets) >= 2  # the probe stream spans ≥2 shapes
        for spec, batch, active in buckets:
            r_df = solve_bucket(batch, active, config=_DF32)
            r_64 = solve_bucket(batch, active, config=_F64)
            sched = [row["engine"] for row in r_df.phase_report]
            assert sched == ["f32", "df32", "f64"]  # the 1e-8 tier
            for k in np.flatnonzero(active):
                assert r_df.status[k] is Status.OPTIMAL, (spec, k)
                assert r_64.status[k] is Status.OPTIMAL, (spec, k)
                assert abs(r_df.objective[k] - r_64.objective[k]) <= 1e-8 * (
                    1.0 + abs(r_64.objective[k])
                ), (spec, k)
                assert r_df.rel_gap[k] <= 1e-8
                assert r_df.pinf[k] <= 1e-7 and r_df.dinf[k] <= 1e-7

    def test_bitwise_stable_and_zero_warm_recompiles(self):
        batch = random_batched_lp(8, 12, 40, seed=21)
        active = np.ones(8, dtype=bool)
        r1 = solve_bucket(batch, active, config=_DF32)
        cache0 = bucket_cache_size()
        r2 = solve_bucket(batch, active, config=_DF32)
        assert bucket_cache_size() == cache0  # warm bucket: no recompile
        assert np.array_equal(r1.x, r2.x)  # bitwise-stable dispatches
        assert np.array_equal(r1.iterations, r2.iterations)

    def test_loose_tier_drops_finisher_phases(self):
        # tolerance tiers: 1e-4 stops at df32, 1e-2 runs f32 alone —
        # both with honest OPTIMAL verdicts.
        batch = random_batched_lp(4, 8, 24, seed=3)
        active = np.ones(4, dtype=bool)
        r_mid = solve_bucket(batch, active, config=_DF32.replace(tol=1e-4))
        assert [r["engine"] for r in r_mid.phase_report] == ["f32", "df32"]
        r_loose = solve_bucket(batch, active, config=_DF32.replace(tol=1e-2))
        assert [r["engine"] for r in r_loose.phase_report] == ["f32"]
        for r in (r_mid, r_loose):
            assert all(s is Status.OPTIMAL for s in r.status)

    def test_fused_iters_bitwise_equivalent(self):
        batch = random_batched_lp(6, 10, 32, seed=8)
        active = np.array([True] * 5 + [False])
        r1 = solve_bucket(batch, active, config=_F64.replace(fused_iters=1))
        r4 = solve_bucket(batch, active, config=_F64.replace(fused_iters=4))
        assert r4.fused_iters == 4
        assert np.array_equal(r1.x, r4.x)
        assert np.array_equal(r1.iterations, r4.iterations)
        assert list(r1.status) == list(r4.status)
        # and composed with the df32 schedule
        d1 = solve_bucket(batch, active, config=_DF32.replace(fused_iters=1))
        d3 = solve_bucket(batch, active, config=_DF32.replace(fused_iters=3))
        assert np.array_equal(d1.x, d3.x)


class TestSegmentedDispatch:
    def test_segmented_matches_fused_and_donates(self):
        batch = random_batched_lp(8, 12, 40, seed=5)
        active = np.ones(8, dtype=bool)
        cfg = _DF32.replace(segment_iters=4)
        r_seg = solve_bucket(batch, active, cfg)
        r_one = solve_bucket(batch, active, _DF32)
        assert all(s is Status.OPTIMAL for s in r_seg.status)
        np.testing.assert_allclose(r_seg.x, r_one.x, rtol=1e-8, atol=1e-10)
        # repeat dispatch through the segmented path: warm, stable
        cache0 = bucket_cache_size()
        r_seg2 = solve_bucket(batch, active, cfg)
        assert bucket_cache_size() == cache0
        assert np.array_equal(r_seg.x, r_seg2.x)

    def test_donation_no_aliasing_copy(self):
        # The compiled segment program must alias the donated carry into
        # its outputs (alias bytes cover at least the (B, n) f64 state
        # lanes) — 0 would mean the donation is silently copied.
        report = bucket_donation_report(12, 40, 8)
        if report is None or report.get("alias_bytes") is None:
            pytest.skip("backend exposes no memory analysis")
        assert report["alias_bytes"] >= 8 * 40 * 8  # one (B, n) f64 lane


class TestServiceIntegration:
    def test_service_df32_schedule_telemetry_and_zero_recompile(self, tmp_path):
        log = tmp_path / "serve.jsonl"
        cfg = ServiceConfig(batch=8, flush_s=0.02, log_jsonl=str(log))
        with SolveService(cfg, solver_config=_DF32) as svc:
            futs = [svc.submit(p) for p in random_request_stream(40, seed=5)]
            assert svc.drain(timeout=600)
            results = [f.result(timeout=30) for f in futs]
            cache0 = bucket_cache_size()
            warm = [svc.submit(p) for p in random_request_stream(24, seed=6)]
            assert svc.drain(timeout=600)
            warm_results = [f.result(timeout=30) for f in warm]
            assert bucket_cache_size() == cache0  # zero warm recompiles
            stats = svc.stats()
        assert all(
            r.status is Status.OPTIMAL for r in results + warm_results
        )
        assert stats["schedule"] == "df32"
        assert stats["fused_iters"] >= 1
        assert stats["phase_iters"].get("f32", 0) > 0
        assert stats["phase_iters"].get("df32", 0) > 0
        events = [json.loads(l) for l in log.read_text().splitlines()]
        batches = [e for e in events if e["event"] == "batch"]
        assert batches
        for e in batches:
            assert e["schedule"] == "f32@3e-05→df32@1e-08→f64@1e-08"
            assert e["fused_iters"] >= 1

    def test_warm_buckets_logs_cache_line(self, tmp_path):
        log = tmp_path / "warm.jsonl"
        # A shape no other test warms, so this service really compiles.
        spec = BucketSpec(9, 44, 4)
        with SolveService(
            ServiceConfig(batch=4, log_jsonl=str(log)), auto_start=True
        ) as svc:
            assert svc.warm_buckets([spec]) == 1
        events = [json.loads(l) for l in log.read_text().splitlines()]
        warm = [e for e in events if e["event"] == "warmup"]
        assert len(warm) == 1
        assert warm[0]["bucket"] == [9, 44, 4]
        assert warm[0]["cache"] in ("hit", "miss", "off")
