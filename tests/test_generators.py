"""Generator tests: every generated problem must be feasible and bounded
(verified via the scipy HiGHS oracle at small sizes — SURVEY.md §4)."""

import numpy as np
import pytest
import scipy.sparse as sp

from tests.oracle import highs_on_interior
from distributedlpsolver_tpu.models import (
    block_angular_lp,
    random_batched_lp,
    random_dense_lp,
    random_general_lp,
    to_interior_form,
)


@pytest.mark.parametrize(
    "factory",
    [
        lambda s: random_dense_lp(8, 15, seed=s),
        lambda s: random_general_lp(8, 14, seed=s),
        lambda s: block_angular_lp(3, 4, 7, 2, seed=s),
    ],
)
@pytest.mark.parametrize("seed", [0, 5])
def test_generated_problems_solvable(factory, seed):
    p = factory(seed)
    res = highs_on_interior(to_interior_form(p))
    assert res.status == 0, f"{p.name}: {res.message}"


def test_block_angular_structure():
    p = block_angular_lp(4, 3, 5, 2, seed=1)
    assert p.shape == (4 * 3 + 2, 4 * 5)
    assert p.block_structure["num_blocks"] == 4
    A = np.asarray(p.A)
    # off-diagonal block region is zero
    assert np.all(A[0:3, 5:20] == 0)
    assert np.all(A[3:6, 0:5] == 0)
    # linking rows occupy the last link_m rows
    assert A[12:, :].any()


def test_block_angular_sparse():
    p = block_angular_lp(3, 4, 6, 2, seed=0, sparse=True)
    assert sp.issparse(p.A)


def test_batched_each_solvable():
    bat = random_batched_lp(4, 6, 12, seed=2)
    assert bat.batch == 4
    for k in range(bat.batch):
        res = highs_on_interior(to_interior_form(bat.problem(k)))
        assert res.status == 0
