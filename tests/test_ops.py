"""Pallas fused normal-equations kernel vs the plain-XLA oracle.

Runs in interpret mode so it exercises the kernel logic (tiling,
accumulation, padding) on the CPU test mesh without TPU hardware
(SURVEY.md §4's fake-backend strategy).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributedlpsolver_tpu.ops import (
    normal_eq,
    normal_eq_pallas,
    normal_eq_reference,
    supports_pallas,
)


@pytest.mark.parametrize(
    "m,n",
    [
        (32, 64),  # exact tile fit (with small blocks)
        (100, 300),  # ragged in both axes
        (257, 130),  # m > n, ragged
        (1, 7),  # degenerate tiny
    ],
)
def test_pallas_matches_reference(m, n):
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    d = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    M = normal_eq_pallas(A, d, block_m=128, block_k=128, interpret=True)
    Mr = normal_eq_reference(A, d)
    np.testing.assert_allclose(np.asarray(M), np.asarray(Mr), rtol=2e-5, atol=1e-5)


def test_pallas_accumulates_over_k_tiles():
    # n spans multiple k-tiles — checks the accumulator zero/flush logic.
    rng = np.random.default_rng(1)
    m, n = 64, 700
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    d = jnp.asarray(rng.random(n) + 0.5, jnp.float32)
    M = normal_eq_pallas(A, d, block_m=64, block_k=128, interpret=True)
    Mr = normal_eq_reference(A, d)
    # f32 accumulation order differs between the tiled kernel and XLA.
    np.testing.assert_allclose(np.asarray(M), np.asarray(Mr), rtol=2e-4, atol=1e-4)


def test_dispatch_falls_back_off_tpu():
    rng = np.random.default_rng(2)
    A = jnp.asarray(rng.standard_normal((16, 24)), jnp.float64)
    d = jnp.asarray(rng.random(24) + 0.1, jnp.float64)
    # f64 is never pallas-eligible; dispatch must silently use the XLA path.
    assert not supports_pallas(jnp.float64)
    M = normal_eq(A, d)
    np.testing.assert_allclose(np.asarray(M), np.asarray(normal_eq_reference(A, d)))


def test_result_is_symmetric_psd_shaped():
    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.standard_normal((40, 90)), jnp.float32)
    d = jnp.asarray(rng.random(90) + 0.1, jnp.float32)
    M = np.asarray(normal_eq_pallas(A, d, block_m=64, block_k=64, interpret=True))
    assert M.shape == (40, 40)
    np.testing.assert_allclose(M, M.T, rtol=1e-5, atol=1e-6)
    assert np.linalg.eigvalsh(M).min() > -1e-4
