"""Mesh-distributed Cholesky + triangular inversion (ops/dist_chol.py) —
the second distributed-factorization cut (SURVEY.md §2.2, VERDICT round 3
item 6): unlike round 3's sharded-TRSM-only build, no stage may
materialize a replicated m×m buffer on any device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributedlpsolver_tpu.ops.dist_chol import chol_tri_inv_mesh
from distributedlpsolver_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh((8,), axis_names=("cols",))


def _spd(m, seed=0):
    rng = np.random.default_rng(seed)
    G = rng.standard_normal((m, m))
    return G @ G.T + m * np.eye(m)


@pytest.mark.parametrize(
    "m,panel,dtype,tol",
    [
        (96, 8, jnp.float64, 1e-12),   # divisible: w=12, pad-free
        (130, 16, jnp.float64, 1e-12), # ragged: slab padded to panel mult
        (200, 32, jnp.float32, 5e-6),  # f32 (the production factor dtype)
        (8, 4, jnp.float64, 1e-12),    # one column per device
    ],
)
def test_matches_replicated_factorization(mesh8, m, panel, dtype, tol):
    sh = NamedSharding(mesh8, P(None, "cols"))
    Ms = _spd(m)
    ref = np.linalg.inv(np.linalg.cholesky(Ms))
    got = np.asarray(
        jax.jit(lambda M: chol_tri_inv_mesh(M, sh, panel=panel))(
            jnp.asarray(Ms, dtype)
        )
    )
    err = np.abs(got - ref).max() / np.abs(ref).max()
    assert err < tol, err


def test_output_is_column_sharded(mesh8):
    sh = NamedSharding(mesh8, P(None, "cols"))
    out = jax.jit(lambda M: chol_tri_inv_mesh(M, sh, panel=8))(
        jnp.asarray(_spd(64), jnp.float32)
    )
    spec = out.sharding.spec
    assert tuple(spec) == (None, "cols"), spec


def test_memory_beats_round3_replicated_cholesky(mesh8):
    """Per-device compiled peak of the full distributed pipeline must be
    measurably below the round-3 path (replicated jnp Cholesky feeding
    the sharded TRSM slabs), whose replicated Ms and L buffers are the
    multi-chip HBM ceiling this cut removes."""
    from distributedlpsolver_tpu.backends import dense as D

    sh = NamedSharding(mesh8, P(None, "cols"))
    m = 1024
    Ms = jnp.asarray(_spd(m), jnp.float32)

    def peak(fn):
        comp = jax.jit(fn).lower(Ms).compile()
        return comp.memory_analysis().temp_size_in_bytes

    new = peak(lambda M: chol_tri_inv_mesh(M, sh, panel=128))
    old = peak(lambda M: D._tri_inv_mesh(jnp.linalg.cholesky(M), sh))
    # The old path's replicated L alone is m²·4 bytes on every device;
    # demand at least half of that as the margin (buffer reuse hides
    # part of the win from temp accounting).
    assert new < old - 2 * m * m, (new, old)


def test_preconditioner_path_end_to_end(mesh8):
    """The sharded PCG backend must route through the distributed
    factorization and still converge to the same optimum as the
    replicated dense solve."""
    from distributedlpsolver_tpu.backends.sharded import ShardedJaxBackend
    from distributedlpsolver_tpu.ipm import solve
    from distributedlpsolver_tpu.models.generators import random_dense_lp

    p = random_dense_lp(48, 120, seed=11)
    r_ref = solve(p, backend="cpu")
    be = ShardedJaxBackend(mesh=mesh8)
    r = solve(p, backend=be, solve_mode="pcg")
    assert r.status.value == "optimal"
    assert r.objective == pytest.approx(r_ref.objective, rel=1e-6)


def test_memory_ragged_m_stays_sharded(mesh8):
    """Ragged m (padding path): the identity-tail construction must not
    materialize an unconstrained replicated (mp, mp) buffer (ADVICE
    round 4). Envelope: the ragged case's compiled peak stays within 40%
    of the divisible case at comparable size (the pad itself adds rows,
    so exact equality is not expected — a replicated intermediate would
    roughly DOUBLE it)."""
    sh = NamedSharding(mesh8, P(None, "cols"))

    def peak(m, panel):
        Ms = jnp.asarray(_spd(m), jnp.float32)
        comp = jax.jit(
            lambda M: chol_tri_inv_mesh(M, sh, panel=panel)
        ).lower(Ms).compile()
        return comp.memory_analysis().temp_size_in_bytes

    ragged = peak(1000, 128)   # 1000 -> slab 125 -> pad to 128*8 = 1024
    exact = peak(1024, 128)
    assert ragged < 1.4 * exact, (ragged, exact)
    # and the math survives the pad (oracle check at the ragged size)
    m = 1000
    Ms = jnp.asarray(_spd(m), jnp.float64)
    Linv = np.asarray(chol_tri_inv_mesh(Ms, sh, panel=128))
    err = np.abs(Linv.T @ Linv @ np.asarray(Ms) - np.eye(m)).max()
    assert err < 1e-6, err


def test_block_linking_factor_distributes_over_mesh(mesh8):
    """VERDICT round-4 item 7: with a mesh, the block backend's
    link x link Schur factorization must route through chol_tri_inv_mesh
    (column-sharded factor) instead of replicating it on every device.
    Compile-time per-device temp peak of one f64c segment program at
    link=1600 must drop measurably vs the replicated route."""
    import jax.numpy as jnp
    from distributedlpsolver_tpu.backends import block_angular as B
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.ipm import core as C
    from distributedlpsolver_tpu.models.generators import block_angular_lp
    from distributedlpsolver_tpu.models.problem import to_interior_form

    link = 1600
    p = block_angular_lp(8, 24, 48, link, seed=0, sparse=True, density=0.02)
    inf = to_interior_form(p)

    def peak(link_shard):
        be = B.BlockAngularBackend(mesh=mesh8 if link_shard else None)
        be.setup(inf, SolverConfig())
        lay, t = be._lay, be._tensors
        data = be._data
        params = SolverConfig().step_params()
        buf_cap = C.buffer_cap(200)
        state = be.starting_point()
        carry = C.fresh_segment_carry(
            state, jnp.asarray(1e-10, jnp.float64), buf_cap, jnp.float64
        )
        lowered = B._block_segment.lower(
            t, None, lay, data, carry, jnp.asarray(4, jnp.int32),
            jnp.asarray(8, jnp.int32), jnp.asarray(3, jnp.int32),
            jnp.asarray(100.0, jnp.float64), params, buf_cap,
            mode="f64c", link_shard=be._link_shard,
        )
        return lowered.compile().memory_analysis().temp_size_in_bytes

    sharded = peak(True)
    replicated = peak(False)
    # the replicated link x link f64 factor alone is link^2*8 bytes on
    # every device; demand at least half of that as the margin
    assert sharded < replicated - 4 * link * link, (sharded, replicated)
