"""Mesh-distributed Cholesky + triangular inversion (ops/dist_chol.py) —
the second distributed-factorization cut (SURVEY.md §2.2, VERDICT round 3
item 6): unlike round 3's sharded-TRSM-only build, no stage may
materialize a replicated m×m buffer on any device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributedlpsolver_tpu.ops.dist_chol import chol_tri_inv_mesh
from distributedlpsolver_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh((8,), axis_names=("cols",))


def _spd(m, seed=0):
    rng = np.random.default_rng(seed)
    G = rng.standard_normal((m, m))
    return G @ G.T + m * np.eye(m)


@pytest.mark.parametrize(
    "m,panel,dtype,tol",
    [
        (96, 8, jnp.float64, 1e-12),   # divisible: w=12, pad-free
        (130, 16, jnp.float64, 1e-12), # ragged: slab padded to panel mult
        (200, 32, jnp.float32, 5e-6),  # f32 (the production factor dtype)
        (8, 4, jnp.float64, 1e-12),    # one column per device
    ],
)
def test_matches_replicated_factorization(mesh8, m, panel, dtype, tol):
    sh = NamedSharding(mesh8, P(None, "cols"))
    Ms = _spd(m)
    ref = np.linalg.inv(np.linalg.cholesky(Ms))
    got = np.asarray(
        jax.jit(lambda M: chol_tri_inv_mesh(M, sh, panel=panel))(
            jnp.asarray(Ms, dtype)
        )
    )
    err = np.abs(got - ref).max() / np.abs(ref).max()
    assert err < tol, err


def test_output_is_column_sharded(mesh8):
    sh = NamedSharding(mesh8, P(None, "cols"))
    out = jax.jit(lambda M: chol_tri_inv_mesh(M, sh, panel=8))(
        jnp.asarray(_spd(64), jnp.float32)
    )
    spec = out.sharding.spec
    assert tuple(spec) == (None, "cols"), spec


def test_memory_beats_round3_replicated_cholesky(mesh8):
    """Per-device compiled peak of the full distributed pipeline must be
    measurably below the round-3 path (replicated jnp Cholesky feeding
    the sharded TRSM slabs), whose replicated Ms and L buffers are the
    multi-chip HBM ceiling this cut removes."""
    from distributedlpsolver_tpu.backends import dense as D

    sh = NamedSharding(mesh8, P(None, "cols"))
    m = 1024
    Ms = jnp.asarray(_spd(m), jnp.float32)

    def peak(fn):
        comp = jax.jit(fn).lower(Ms).compile()
        return comp.memory_analysis().temp_size_in_bytes

    new = peak(lambda M: chol_tri_inv_mesh(M, sh, panel=128))
    old = peak(lambda M: D._tri_inv_mesh(jnp.linalg.cholesky(M), sh))
    # The old path's replicated L alone is m²·4 bytes on every device;
    # demand at least half of that as the margin (buffer reuse hides
    # part of the win from temp accounting).
    assert new < old - 2 * m * m, (new, old)


def test_preconditioner_path_end_to_end(mesh8):
    """The sharded PCG backend must route through the distributed
    factorization and still converge to the same optimum as the
    replicated dense solve."""
    from distributedlpsolver_tpu.backends.sharded import ShardedJaxBackend
    from distributedlpsolver_tpu.ipm import solve
    from distributedlpsolver_tpu.models.generators import random_dense_lp

    p = random_dense_lp(48, 120, seed=11)
    r_ref = solve(p, backend="cpu")
    be = ShardedJaxBackend(mesh=mesh8)
    r = solve(p, backend=be, solve_mode="pcg")
    assert r.status.value == "optimal"
    assert r.objective == pytest.approx(r_ref.objective, rel=1e-6)
