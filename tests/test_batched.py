"""Batched-solver tests (BASELINE.json:11 workload, SURVEY.md §4
"vmap'd solve equals per-problem loop solve")."""

import jax
import numpy as np
import pytest

from distributedlpsolver_tpu.backends.batched import solve_batched
from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.generators import random_batched_lp
from distributedlpsolver_tpu.parallel import make_mesh
from tests.oracle import highs_on_general


@pytest.fixture(scope="module")
def batch():
    return random_batched_lp(12, 16, 40, seed=3)


@pytest.fixture(scope="module")
def result(batch):
    return solve_batched(batch)


def test_all_converge(batch, result):
    assert result.n_optimal == batch.batch
    assert (result.rel_gap <= 1e-8).all()
    assert (result.pinf <= 1e-7).all()


def test_matches_per_problem_solve(batch, result):
    for k in [0, 4, 9]:
        r = solve(batch.problem(k), backend="tpu")
        assert r.status == Status.OPTIMAL
        assert result.objective[k] == pytest.approx(r.objective, rel=1e-9, abs=1e-9)


def test_matches_highs(batch, result):
    for k in [1, 7]:
        hi = highs_on_general(batch.problem(k))
        assert result.objective[k] == pytest.approx(hi.fun, rel=1e-6)


def test_ragged_convergence_masking(batch, result):
    """Problems converge at different iteration counts; each must report
    its own count (masking, not a common early exit)."""
    assert result.iterations.min() >= 1
    assert len(set(result.iterations.tolist())) > 1  # genuinely ragged


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_batch_sharded_over_mesh(batch):
    """DP in this domain: shard the batch axis; results must match the
    unsharded solve exactly (SURVEY.md §2.2)."""
    mesh = make_mesh(axis_names=("batch",))
    unsharded = solve_batched(batch)
    # pad batch 12 → 16 not needed: 12 not divisible by 8 → use batch of 16
    b16 = random_batched_lp(16, 16, 40, seed=3)
    r_mesh = solve_batched(b16, mesh=mesh)
    r_ref = solve_batched(b16)
    assert r_mesh.n_optimal == 16
    np.testing.assert_allclose(r_mesh.objective, r_ref.objective, rtol=1e-9)
    with pytest.raises(ValueError):
        solve_batched(batch, mesh=mesh)  # 12 % 8 != 0


def test_pcg_middle_phase_full_tol(batch):
    """solve_mode="pcg" adds the full-tolerance PCG middle phase (f32
    preconditioner + f64 matrix-free CG). Every member must still reach
    1e-8 with objectives matching the direct path."""
    r_pcg = solve_batched(batch, solve_mode="pcg")
    r_dir = solve_batched(batch)
    assert r_pcg.n_optimal == len(r_pcg.status)
    assert (r_pcg.rel_gap <= 1e-8).all() and (r_pcg.pinf <= 1e-8).all()
    np.testing.assert_allclose(r_pcg.objective, r_dir.objective, rtol=1e-8)


def test_pcg_phase_keeps_optimal_members_settled():
    """Members a full-tol phase proved OPTIMAL must NOT re-enter the next
    phase: the keep-optimal carry reset leaves them inactive and settled
    (this boundary is the PCG middle phase's whole payoff), while the
    provisional reset re-activates everyone."""
    import jax.numpy as jnp
    import distributedlpsolver_tpu.backends.batched as bt

    B = 6
    states = jnp.zeros((B, 3))  # any pytree-of-arrays works for the reset
    iters = jnp.arange(B, dtype=jnp.int32)
    status = jnp.asarray(
        [bt._OPTIMAL, bt._RUNNING, bt._OPTIMAL, bt._STALL, bt._NUMERR,
         bt._RUNNING], jnp.int32
    )
    carry = bt._fresh_batch_carry(
        states, iters, B, 1e-10, jnp.float64, status=status
    )
    active, new_status = np.asarray(carry[1]), np.asarray(carry[5])
    # optimal members settled+inactive; everyone else re-activated RUNNING
    np.testing.assert_array_equal(
        active, [False, True, False, True, True, True]
    )
    np.testing.assert_array_equal(
        new_status,
        [bt._OPTIMAL, bt._RUNNING, bt._OPTIMAL, bt._RUNNING, bt._RUNNING,
         bt._RUNNING],
    )
    np.testing.assert_array_equal(np.asarray(carry[6]), np.asarray(iters))
    # provisional reset (status=None): everyone re-enters
    carry2 = bt._fresh_batch_carry(states, iters, B, 1e-10, jnp.float64)
    assert np.asarray(carry2[1]).all()
    assert (np.asarray(carry2[5]) == bt._RUNNING).all()


def test_final_phase_compaction_matches_plain():
    # Per-member column scaling staggers convergence (iters ~9..29), so
    # the segmented drive's actives fall below half the program size and
    # compaction shrinks 64 -> 32 while stragglers finish. The compacted
    # path must agree with the unsegmented whole-batch solve
    # member-for-member (same math, smaller programs) — including on the
    # members that do NOT reach optimality.
    from unittest import mock

    from distributedlpsolver_tpu.backends import batched as batched_mod
    from distributedlpsolver_tpu.models.generators import BatchedLP

    b = random_batched_lp(64, 16, 40, seed=11)
    rng = np.random.default_rng(0)
    A = np.asarray(b.A) * 10.0 ** rng.uniform(-1, 1, (64, 1, 40))
    b2 = BatchedLP(c=b.c, A=A, b=b.b, name="staggered")
    r_plain = solve_batched(b2, segment_iters=0)
    calls = []
    orig = batched_mod._compact_gather
    with mock.patch.object(
        batched_mod, "_compact_gather",
        side_effect=lambda *a, **k: calls.append(a[3]) or orig(*a, **k),
    ):
        r_comp = solve_batched(b2, segment_iters=2)
    assert calls, "compaction never triggered — the staggered batch no longer staggers"
    assert all(s <= 32 for s in calls)
    assert r_comp.n_optimal == r_plain.n_optimal
    np.testing.assert_allclose(r_comp.objective, r_plain.objective, rtol=1e-6)
