"""Shared scipy-HiGHS oracle helpers for tests (SURVEY.md §4).

One implementation of "solve this with HiGHS" for both the interior form and
the original general form, so every test module validates against the same
oracle.
"""

import numpy as np
import scipy.optimize as sopt
import scipy.sparse as sp


def highs_on_interior(inf):
    """Solve an InteriorForm LP with scipy HiGHS (min cᵀx, Ax=b, 0≤x≤u)."""
    A = inf.A.toarray() if sp.issparse(inf.A) else np.asarray(inf.A)
    return sopt.linprog(
        inf.c,
        A_eq=A,
        b_eq=inf.b,
        bounds=[(0.0, u if np.isfinite(u) else None) for u in inf.u],
        method="highs",
    )


def highs_on_general(p):
    """Solve a general-form LPProblem with scipy HiGHS (row bounds as ub pairs)."""
    A = p.A.toarray() if sp.issparse(p.A) else np.asarray(p.A)
    eq = (p.rlb == p.rub) & np.isfinite(p.rlb)
    A_ub, b_ub = [], []
    for i in range(p.m):
        if eq[i]:
            continue
        if np.isfinite(p.rub[i]):
            A_ub.append(A[i])
            b_ub.append(p.rub[i])
        if np.isfinite(p.rlb[i]):
            A_ub.append(-A[i])
            b_ub.append(-p.rlb[i])
    return sopt.linprog(
        p.c,
        A_ub=np.array(A_ub) if A_ub else None,
        b_ub=np.array(b_ub) if b_ub else None,
        A_eq=A[eq] if eq.any() else None,
        b_eq=p.rlb[eq] if eq.any() else None,
        bounds=[
            (
                p.lb[j] if np.isfinite(p.lb[j]) else None,
                p.ub[j] if np.isfinite(p.ub[j]) else None,
            )
            for j in range(p.n)
        ],
        method="highs",
    )
