"""MPS reader/writer tests: hand-written fixtures with known semantics plus
write→read round-trips on random general LPs (SURVEY.md §4 unit plan)."""

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.io import read_mps, read_mps_string, write_mps
from distributedlpsolver_tpu.models import random_general_lp

TINY = """\
* tiny test problem
NAME          TINY
ROWS
 N  COST
 L  LIM1
 G  LIM2
 E  MYEQN
COLUMNS
    X1        COST         1.0   LIM1         1.0
    X1        LIM2         1.0
    X2        COST         2.0   LIM1         1.0
    X2        MYEQN       -1.0
    X3        COST        -1.0   MYEQN        1.0
RHS
    RHS1      LIM1         4.0   LIM2         1.0
    RHS1      MYEQN        7.0
BOUNDS
 UP BND1      X1           4.0
 LO BND1      X2          -1.0
ENDATA
"""


class TestReader:
    def test_tiny(self):
        p = read_mps_string(TINY)
        assert p.name == "TINY"
        assert p.shape == (3, 3)
        np.testing.assert_allclose(p.c, [1.0, 2.0, -1.0])
        A = np.asarray(p.A)
        np.testing.assert_allclose(
            A, [[1.0, 1.0, 0.0], [1.0, 0.0, 0.0], [0.0, -1.0, 1.0]]
        )
        np.testing.assert_allclose(p.rlb, [-np.inf, 1.0, 7.0])
        np.testing.assert_allclose(p.rub, [4.0, np.inf, 7.0])
        np.testing.assert_allclose(p.lb, [0.0, -1.0, 0.0])
        np.testing.assert_allclose(p.ub, [4.0, np.inf, np.inf])

    def test_objective_constant_and_maximize(self):
        text = """\
NAME X
OBJSENSE
    MAX
ROWS
 N obj
 L r1
COLUMNS
    x obj 3.0 r1 1.0
RHS
    RHS obj 5.0 r1 10.0
ENDATA
"""
        p = read_mps_string(text)
        # RHS 5.0 on the obj row ⇒ constant −5, so this is MAX 3x − 5,
        # stored as MIN −3x + 5.
        np.testing.assert_allclose(p.c, [-3.0])
        assert p.c0 == 5.0
        assert p.maximize

    def test_ranges(self):
        text = """\
NAME R
ROWS
 N obj
 L l1
 G g1
 E e1
 E e2
COLUMNS
    x obj 1.0 l1 1.0
    x g1 1.0 e1 1.0
    x e2 1.0
RHS
    R l1 10.0 g1 2.0
    R e1 5.0 e2 5.0
RANGES
    RNG l1 4.0 g1 3.0
    RNG e1 2.0 e2 -2.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose(p.rlb, [6.0, 2.0, 5.0, 3.0])
        np.testing.assert_allclose(p.rub, [10.0, 5.0, 7.0, 5.0])

    def test_negative_up_bound_quirk(self):
        text = """\
NAME Q
ROWS
 N obj
 E e1
COLUMNS
    x obj 1.0 e1 1.0
RHS
    R e1 1.0
BOUNDS
 UP B x -2.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.ub[0] == -2.0
        assert p.lb[0] == -np.inf  # classic quirk fired

    def test_integer_markers_relaxed(self):
        text = """\
NAME I
ROWS
 N obj
 G r
COLUMNS
    MARKER                 'MARKER'                 'INTORG'
    xi obj 1.0 r 1.0
    MARKER                 'MARKER'                 'INTEND'
    xc obj 1.0 r 1.0
RHS
    R r 2.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.integer_cols == [0]
        assert p.shape == (1, 2)

    def test_free_extra_n_rows_dropped(self):
        text = """\
NAME F
ROWS
 N obj
 N freerow
 E e1
COLUMNS
    x obj 1.0 freerow 9.0
    x e1 1.0
RHS
    R e1 1.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.shape == (1, 1)

    def test_duplicate_entries_summed(self):
        text = """\
NAME D
ROWS
 N obj
 E e1
COLUMNS
    x obj 1.0 e1 1.0
    x e1 2.0
RHS
    R e1 3.0
ENDATA
"""
        p = read_mps_string(text)
        assert np.asarray(p.A)[0, 0] == 3.0

    def test_sparse_output(self):
        p = read_mps_string(TINY, dense=False)
        assert sp.issparse(p.A)


class TestRoundTrip:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_write_read_roundtrip(self, tmp_path, seed):
        p = random_general_lp(8, 13, seed=seed)
        path = tmp_path / "rt.mps"
        write_mps(p, path)
        q = read_mps(path)
        np.testing.assert_allclose(q.c, p.c, rtol=1e-15)
        np.testing.assert_allclose(np.asarray(q.A), np.asarray(p.A), rtol=1e-15)
        np.testing.assert_allclose(q.rlb, p.rlb, rtol=1e-12)
        np.testing.assert_allclose(q.rub, p.rub, rtol=1e-12)
        np.testing.assert_allclose(q.lb, p.lb, rtol=1e-15)
        np.testing.assert_allclose(q.ub, p.ub, rtol=1e-15)

    def test_gzip_roundtrip(self, tmp_path):
        import gzip

        p = random_general_lp(5, 7, seed=3)
        path = tmp_path / "rt.mps"
        write_mps(p, path)
        gz = tmp_path / "rt.mps.gz"
        with open(path, "rb") as f, gzip.open(gz, "wb") as g:
            g.write(f.read())
        q = read_mps(gz)
        np.testing.assert_allclose(np.asarray(q.A), np.asarray(p.A))


class TestReviewRegressions:
    """Regressions for the round-trip/parsing bugs found in code review."""

    def test_zero_column_survives_roundtrip(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([0.0, 1.0]),
            A=np.array([[0.0, 1.0]]),  # col 0 appears nowhere
            rlb=np.array([1.0]),
            rub=np.array([1.0]),
            lb=np.zeros(2),
            ub=np.array([5.0, np.inf]),
        )
        path = tmp_path / "zero.mps"
        write_mps(p, path)
        q = read_mps(path)
        assert q.n == 2
        np.testing.assert_allclose(q.ub, p.ub)

    def test_obj_name_collision(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([2.0]), A=np.array([[1.0]]),
            rlb=np.array([-np.inf]), rub=np.array([3.0]),
            lb=np.zeros(1), ub=np.array([np.inf]),
            row_names=["OBJ"], col_names=["x"],
        )
        path = tmp_path / "obj.mps"
        write_mps(p, path)
        q = read_mps(path)
        assert q.m == 1
        np.testing.assert_allclose(q.c, [2.0])
        np.testing.assert_allclose(np.asarray(q.A), [[1.0]])
        np.testing.assert_allclose(q.rub, [3.0])

    def test_coefficient_on_row_named_marker(self):
        import numpy as np

        text = """\
NAME M
ROWS
 N obj
 E MARKER
COLUMNS
    X1 MARKER 2.0
    X1 obj 1.0
RHS
    R MARKER 4.0
ENDATA
"""
        p = read_mps_string(text)
        assert np.asarray(p.A)[0, 0] == 2.0
        assert p.rlb[0] == 4.0

    def test_rhs_setname_collides_with_row(self):
        import numpy as np

        # RHS set named like a row: parity rule must still parse correctly.
        text = """\
NAME C
ROWS
 N obj
 E r1
 E r2
COLUMNS
    x obj 1.0 r1 1.0
    x r2 1.0
RHS
    r1 r1 5.0 r2 6.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose(p.rlb, [5.0, 6.0])

    def test_free_row_emitted_as_n_and_dropped(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([1.0]), A=np.array([[1.0], [2.0]]),
            rlb=np.array([-np.inf, 1.0]), rub=np.array([np.inf, 1.0]),
            lb=np.zeros(1), ub=np.array([np.inf]),
        )
        path = tmp_path / "free.mps"
        write_mps(p, path)
        q = read_mps(path)
        # free row dropped, feasible set preserved
        assert q.m == 1
        np.testing.assert_allclose(q.rlb, [1.0])


class TestAdversarial:
    """Adversarial parser inputs beyond the two hand-written fixtures:
    fixed-format layout quirks, RANGES sign conventions per row type,
    duplicate entries in every section, and a writer-driven fuzz
    round-trip (VERDICT "What's missing" #5)."""

    def test_fixed_format_column_layout(self):
        # Genuine fixed-column layout (fields at columns 2/5/15/25/40/50,
        # wide name fields padded with blanks) plus trailing whitespace —
        # must tokenize identically to free format.
        text = (
            "NAME          FIXED\n"
            "ROWS\n"
            " N  COST\n"
            " L  LIM1      \n"
            " E  EQ2\n"
            "COLUMNS\n"
            "    X1        COST            1.0   LIM1            2.0\n"
            "    X1        EQ2             1.0\n"
            "    X2        COST            3.0   EQ2             1.0   \n"
            "RHS\n"
            "    RHS       LIM1            4.0   EQ2             5.0\n"
            "BOUNDS\n"
            " UP BND       X1              9.0\n"
            "ENDATA\n"
        )
        p = read_mps_string(text)
        assert p.shape == (2, 2)
        np.testing.assert_allclose(np.asarray(p.A), [[2.0, 0.0], [1.0, 1.0]])
        np.testing.assert_allclose(p.c, [1.0, 3.0])
        np.testing.assert_allclose(p.ub, [9.0, np.inf])

    def test_fortran_d_exponents(self):
        # Old fixed-format Netlib files carry Fortran D exponents in
        # values; every value-bearing section must accept them.
        text = """\
NAME D
ROWS
 N obj
 L l1
COLUMNS
    x obj 1.5D+01 l1 -2.5d-01
RHS
    R l1 1.0D2
RANGES
    RNG l1 4.0D0
BOUNDS
 UP B x 1.0D+03
ENDATA
"""
        p = read_mps_string(text)
        assert p.c[0] == 15.0
        assert np.asarray(p.A)[0, 0] == -0.25
        np.testing.assert_allclose([p.rlb[0], p.rub[0]], [96.0, 100.0])
        assert p.ub[0] == 1000.0

    def test_ranges_sign_conventions_all_row_types(self):
        # |r| on L and G regardless of sign; signed convention on E;
        # zero range on E collapses to the equality itself.
        text = """\
NAME R
ROWS
 N obj
 L l1
 L l2
 G g1
 G g2
 E e0
COLUMNS
    x obj 1.0 l1 1.0
    x l2 1.0 g1 1.0
    x g2 1.0 e0 1.0
RHS
    R l1 10.0 l2 10.0
    R g1 2.0 g2 2.0
    R e0 5.0
RANGES
    RNG l1 4.0 l2 -4.0
    RNG g1 3.0 g2 -3.0
    RNG e0 0.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose(p.rlb, [6.0, 6.0, 2.0, 2.0, 5.0])
        np.testing.assert_allclose(p.rub, [10.0, 10.0, 5.0, 5.0, 5.0])

    def test_ranges_on_objective_and_free_rows_ignored(self):
        # A range on an N row has no constraint to widen; classic parsers
        # drop it like RHS entries on free rows — ours must not crash.
        text = """\
NAME N
ROWS
 N obj
 N free2
 L l1
COLUMNS
    x obj 1.0 l1 1.0
RHS
    R l1 8.0
RANGES
    RNG obj 3.0 free2 2.0
    RNG l1 2.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose([p.rlb[0], p.rub[0]], [6.0, 8.0])

    def test_duplicate_entries_within_one_line_summed(self):
        text = """\
NAME D2
ROWS
 N obj
 E e1
COLUMNS
    x obj 1.0 e1 1.0 e1 2.0
    x obj 0.5
RHS
    R e1 3.0
ENDATA
"""
        p = read_mps_string(text)
        assert np.asarray(p.A)[0, 0] == 3.0  # duplicates summed
        assert p.c[0] == 1.5  # objective duplicates summed too

    def test_duplicate_rhs_ranges_bounds_last_wins(self):
        # Pins the overwrite semantics for duplicate RHS/RANGES/BOUNDS
        # entries (classic parsers disagree; ours is last-wins).
        text = """\
NAME D3
ROWS
 N obj
 L l1
COLUMNS
    x obj 1.0 l1 1.0
RHS
    R l1 5.0 l1 9.0
RANGES
    RNG l1 2.0 l1 4.0
BOUNDS
 UP B x 7.0
 UP B x 3.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose([p.rlb[0], p.rub[0]], [5.0, 9.0])
        assert p.ub[0] == 3.0

    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_roundtrip_via_writer(self, tmp_path, seed):
        # Writer-driven fuzz: random general LPs at random shapes (mixed
        # row senses, ranges, boxed/free/one-sided columns) must survive
        # write→read bit-exactly on every field the format carries.
        rng = np.random.default_rng(1000 + seed)
        m = int(rng.integers(2, 20))
        n = int(rng.integers(2, 30))
        p = random_general_lp(m, n, seed=seed)
        path = tmp_path / f"fuzz{seed}.mps"
        write_mps(p, path)
        q = read_mps(path)
        assert q.shape == p.shape
        np.testing.assert_allclose(q.c, p.c, rtol=1e-15)
        np.testing.assert_allclose(
            np.asarray(q.A), np.asarray(p.A), rtol=1e-15
        )
        np.testing.assert_allclose(q.rlb, p.rlb, rtol=1e-12)
        np.testing.assert_allclose(q.rub, p.rub, rtol=1e-12)
        np.testing.assert_allclose(q.lb, p.lb, rtol=1e-15)
        np.testing.assert_allclose(q.ub, p.ub, rtol=1e-15)


def test_objsense_max_round_trip(tmp_path):
    """A maximize problem must survive write->read: OBJSENSE MAX emitted,
    stored-minimized c/c0 identical, and the sense-corrected objective of
    a solve matches."""
    import dataclasses

    from distributedlpsolver_tpu.models.generators import random_general_lp

    p = random_general_lp(8, 14, seed=3)
    pm = dataclasses.replace(p, maximize=True, c=-p.c, c0=1.5)
    path = tmp_path / "maxp.mps"
    write_mps(pm, path)
    assert "OBJSENSE" in path.read_text()
    q = read_mps(path)
    assert q.maximize is True
    np.testing.assert_allclose(q.c, pm.c)
    assert q.c0 == pytest.approx(pm.c0)


def test_columns_odd_fields_clear_error(tmp_path):
    bad = """NAME T
ROWS
 N  OBJ
 E  R1
COLUMNS
    X  OBJ  1.0  R1
RHS
    RHS1  R1  1.0
ENDATA
"""
    path = tmp_path / "bad.mps"
    path.write_text(bad)
    with pytest.raises(ValueError, match="COLUMNS line has"):
        read_mps(path)
