"""MPS reader/writer tests: hand-written fixtures with known semantics plus
write→read round-trips on random general LPs (SURVEY.md §4 unit plan)."""

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.io import read_mps, read_mps_string, write_mps
from distributedlpsolver_tpu.models import random_general_lp

TINY = """\
* tiny test problem
NAME          TINY
ROWS
 N  COST
 L  LIM1
 G  LIM2
 E  MYEQN
COLUMNS
    X1        COST         1.0   LIM1         1.0
    X1        LIM2         1.0
    X2        COST         2.0   LIM1         1.0
    X2        MYEQN       -1.0
    X3        COST        -1.0   MYEQN        1.0
RHS
    RHS1      LIM1         4.0   LIM2         1.0
    RHS1      MYEQN        7.0
BOUNDS
 UP BND1      X1           4.0
 LO BND1      X2          -1.0
ENDATA
"""


class TestReader:
    def test_tiny(self):
        p = read_mps_string(TINY)
        assert p.name == "TINY"
        assert p.shape == (3, 3)
        np.testing.assert_allclose(p.c, [1.0, 2.0, -1.0])
        A = np.asarray(p.A)
        np.testing.assert_allclose(
            A, [[1.0, 1.0, 0.0], [1.0, 0.0, 0.0], [0.0, -1.0, 1.0]]
        )
        np.testing.assert_allclose(p.rlb, [-np.inf, 1.0, 7.0])
        np.testing.assert_allclose(p.rub, [4.0, np.inf, 7.0])
        np.testing.assert_allclose(p.lb, [0.0, -1.0, 0.0])
        np.testing.assert_allclose(p.ub, [4.0, np.inf, np.inf])

    def test_objective_constant_and_maximize(self):
        text = """\
NAME X
OBJSENSE
    MAX
ROWS
 N obj
 L r1
COLUMNS
    x obj 3.0 r1 1.0
RHS
    RHS obj 5.0 r1 10.0
ENDATA
"""
        p = read_mps_string(text)
        # RHS 5.0 on the obj row ⇒ constant −5, so this is MAX 3x − 5,
        # stored as MIN −3x + 5.
        np.testing.assert_allclose(p.c, [-3.0])
        assert p.c0 == 5.0
        assert p.maximize

    def test_ranges(self):
        text = """\
NAME R
ROWS
 N obj
 L l1
 G g1
 E e1
 E e2
COLUMNS
    x obj 1.0 l1 1.0
    x g1 1.0 e1 1.0
    x e2 1.0
RHS
    R l1 10.0 g1 2.0
    R e1 5.0 e2 5.0
RANGES
    RNG l1 4.0 g1 3.0
    RNG e1 2.0 e2 -2.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose(p.rlb, [6.0, 2.0, 5.0, 3.0])
        np.testing.assert_allclose(p.rub, [10.0, 5.0, 7.0, 5.0])

    def test_negative_up_bound_quirk(self):
        text = """\
NAME Q
ROWS
 N obj
 E e1
COLUMNS
    x obj 1.0 e1 1.0
RHS
    R e1 1.0
BOUNDS
 UP B x -2.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.ub[0] == -2.0
        assert p.lb[0] == -np.inf  # classic quirk fired

    def test_integer_markers_relaxed(self):
        text = """\
NAME I
ROWS
 N obj
 G r
COLUMNS
    MARKER                 'MARKER'                 'INTORG'
    xi obj 1.0 r 1.0
    MARKER                 'MARKER'                 'INTEND'
    xc obj 1.0 r 1.0
RHS
    R r 2.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.integer_cols == [0]
        assert p.shape == (1, 2)

    def test_free_extra_n_rows_dropped(self):
        text = """\
NAME F
ROWS
 N obj
 N freerow
 E e1
COLUMNS
    x obj 1.0 freerow 9.0
    x e1 1.0
RHS
    R e1 1.0
ENDATA
"""
        p = read_mps_string(text)
        assert p.shape == (1, 1)

    def test_duplicate_entries_summed(self):
        text = """\
NAME D
ROWS
 N obj
 E e1
COLUMNS
    x obj 1.0 e1 1.0
    x e1 2.0
RHS
    R e1 3.0
ENDATA
"""
        p = read_mps_string(text)
        assert np.asarray(p.A)[0, 0] == 3.0

    def test_sparse_output(self):
        p = read_mps_string(TINY, dense=False)
        assert sp.issparse(p.A)


class TestRoundTrip:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_write_read_roundtrip(self, tmp_path, seed):
        p = random_general_lp(8, 13, seed=seed)
        path = tmp_path / "rt.mps"
        write_mps(p, path)
        q = read_mps(path)
        np.testing.assert_allclose(q.c, p.c, rtol=1e-15)
        np.testing.assert_allclose(np.asarray(q.A), np.asarray(p.A), rtol=1e-15)
        np.testing.assert_allclose(q.rlb, p.rlb, rtol=1e-12)
        np.testing.assert_allclose(q.rub, p.rub, rtol=1e-12)
        np.testing.assert_allclose(q.lb, p.lb, rtol=1e-15)
        np.testing.assert_allclose(q.ub, p.ub, rtol=1e-15)

    def test_gzip_roundtrip(self, tmp_path):
        import gzip

        p = random_general_lp(5, 7, seed=3)
        path = tmp_path / "rt.mps"
        write_mps(p, path)
        gz = tmp_path / "rt.mps.gz"
        with open(path, "rb") as f, gzip.open(gz, "wb") as g:
            g.write(f.read())
        q = read_mps(gz)
        np.testing.assert_allclose(np.asarray(q.A), np.asarray(p.A))


class TestReviewRegressions:
    """Regressions for the round-trip/parsing bugs found in code review."""

    def test_zero_column_survives_roundtrip(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([0.0, 1.0]),
            A=np.array([[0.0, 1.0]]),  # col 0 appears nowhere
            rlb=np.array([1.0]),
            rub=np.array([1.0]),
            lb=np.zeros(2),
            ub=np.array([5.0, np.inf]),
        )
        path = tmp_path / "zero.mps"
        write_mps(p, path)
        q = read_mps(path)
        assert q.n == 2
        np.testing.assert_allclose(q.ub, p.ub)

    def test_obj_name_collision(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([2.0]), A=np.array([[1.0]]),
            rlb=np.array([-np.inf]), rub=np.array([3.0]),
            lb=np.zeros(1), ub=np.array([np.inf]),
            row_names=["OBJ"], col_names=["x"],
        )
        path = tmp_path / "obj.mps"
        write_mps(p, path)
        q = read_mps(path)
        assert q.m == 1
        np.testing.assert_allclose(q.c, [2.0])
        np.testing.assert_allclose(np.asarray(q.A), [[1.0]])
        np.testing.assert_allclose(q.rub, [3.0])

    def test_coefficient_on_row_named_marker(self):
        import numpy as np

        text = """\
NAME M
ROWS
 N obj
 E MARKER
COLUMNS
    X1 MARKER 2.0
    X1 obj 1.0
RHS
    R MARKER 4.0
ENDATA
"""
        p = read_mps_string(text)
        assert np.asarray(p.A)[0, 0] == 2.0
        assert p.rlb[0] == 4.0

    def test_rhs_setname_collides_with_row(self):
        import numpy as np

        # RHS set named like a row: parity rule must still parse correctly.
        text = """\
NAME C
ROWS
 N obj
 E r1
 E r2
COLUMNS
    x obj 1.0 r1 1.0
    x r2 1.0
RHS
    r1 r1 5.0 r2 6.0
ENDATA
"""
        p = read_mps_string(text)
        np.testing.assert_allclose(p.rlb, [5.0, 6.0])

    def test_free_row_emitted_as_n_and_dropped(self, tmp_path):
        import numpy as np
        from distributedlpsolver_tpu.models import LPProblem

        p = LPProblem(
            c=np.array([1.0]), A=np.array([[1.0], [2.0]]),
            rlb=np.array([-np.inf, 1.0]), rub=np.array([np.inf, 1.0]),
            lb=np.zeros(1), ub=np.array([np.inf]),
        )
        path = tmp_path / "free.mps"
        write_mps(p, path)
        q = read_mps(path)
        # free row dropped, feasible set preserved
        assert q.m == 1
        np.testing.assert_allclose(q.rlb, [1.0])


def test_objsense_max_round_trip(tmp_path):
    """A maximize problem must survive write->read: OBJSENSE MAX emitted,
    stored-minimized c/c0 identical, and the sense-corrected objective of
    a solve matches."""
    import dataclasses

    from distributedlpsolver_tpu.models.generators import random_general_lp

    p = random_general_lp(8, 14, seed=3)
    pm = dataclasses.replace(p, maximize=True, c=-p.c, c0=1.5)
    path = tmp_path / "maxp.mps"
    write_mps(pm, path)
    assert "OBJSENSE" in path.read_text()
    q = read_mps(path)
    assert q.maximize is True
    np.testing.assert_allclose(q.c, pm.c)
    assert q.c0 == pytest.approx(pm.c0)


def test_columns_odd_fields_clear_error(tmp_path):
    bad = """NAME T
ROWS
 N  OBJ
 E  R1
COLUMNS
    X  OBJ  1.0  R1
RHS
    RHS1  R1  1.0
ENDATA
"""
    path = tmp_path / "bad.mps"
    path.write_text(bad)
    with pytest.raises(ValueError, match="COLUMNS line has"):
        read_mps(path)
