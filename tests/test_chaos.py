"""Deterministic chaos harness tests (README "Durability & graceful
shutdown"): seeded schedule determinism, WAL-tail truncation and
duplicate accounting helpers, the shared backend registry's consistency
rules, and the probe_chaos.py tier-1 smoke — the multi-process
acceptance run (2 routers + 2 backends, kill -9 / torn tail / restart /
drain under a seeded fault schedule).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from distributedlpsolver_tpu.net.chaos import (
    ChaosPlane,
    ChaosSchedule,
    journal_duplicate_solves,
)
from distributedlpsolver_tpu.net.registry import BackendRegistry
from distributedlpsolver_tpu.obs.metrics import MetricsRegistry

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- schedule ----------------------------------------------------------------


def test_seeded_schedule_is_deterministic_and_ordered():
    a = ChaosSchedule.seeded(7)
    b = ChaosSchedule.seeded(7)
    assert [(e.at_frac, e.kind, e.target) for e in a.events] == [
        (e.at_frac, e.kind, e.target) for e in b.events
    ]
    assert ChaosSchedule.seeded(8).events != a.events
    fracs = [e.at_frac for e in a.events]
    assert fracs == sorted(fracs)
    # The acceptance scenario's legs are all present.
    kinds = [(e.kind, e.target) for e in a.events]
    assert ("kill9", "backend-b") in kinds
    assert ("restart", "backend-a") in kinds
    assert ("torn_tail", "backend-a") in kinds
    assert ("kill9", "router-2") in kinds


def test_schedule_due_fires_each_event_once_in_order():
    sched = ChaosSchedule.seeded(3)
    fired = []
    for frac in (0.0, 0.3, 0.3, 0.6, 1.0):
        fired.extend(e.kind for e in sched.due(frac))
    assert fired == [e.kind for e in ChaosSchedule.seeded(3).events]
    assert sched.due(1.0) == []


# -- helpers -----------------------------------------------------------------


def test_torn_tail_truncates_wal(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "journal.jsonl")
    with open(path, "w") as fh:
        fh.write('{"j": "meta", "nonce": "ab", "next_seq": 0}\n')
        fh.write('{"j": "admitted", "jid": "jab-1"}\n')
    size = os.path.getsize(path)
    assert ChaosPlane.torn_tail(d, nbytes=9)
    assert os.path.getsize(path) == size - 9
    # The journal replays around it (torn counted, not raised).
    from distributedlpsolver_tpu.serve.journal import JobJournal

    j = JobJournal(d)
    assert j.replay().torn == 1
    j.close()


def test_journal_duplicate_solves_counts_per_jid(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "journal.jsonl"), "w") as fh:
        for jid, n in (("jx-1", 1), ("jx-2", 3), ("jx-3", 2)):
            for _ in range(n):
                fh.write(json.dumps({"j": "finished", "jid": jid}) + "\n")
        fh.write("garbage-line\n")
    assert journal_duplicate_solves(d) == 3  # (3-1) + (2-1)
    assert journal_duplicate_solves(str(tmp_path / "absent")) == 0


# -- shared registry consistency rules ---------------------------------------


def _reg(tmp_path, name="r"):
    return BackendRegistry(
        str(tmp_path / "registry.json"),
        writer_id=name,
        metrics=MetricsRegistry(),
    )


def test_registry_ensure_and_atomic_generation(tmp_path):
    r = _reg(tmp_path)
    r.ensure(["http://b1:1/", "http://b2:2"])
    data = r.load()
    assert set(data["backends"]) == {"http://b1:1", "http://b2:2"}
    g0 = data["generation"]
    r.ensure(["http://b1:1"])  # no-op: no new URL
    assert r.load()["generation"] == g0
    assert r.version() > 0


def test_registry_stale_writer_cannot_clobber(tmp_path):
    r1, r2 = _reg(tmp_path, "r1"), _reg(tmp_path, "r2")
    now = time.time()
    assert r1.record("http://b:1", ejected=True, fails=3, observed_ts=now)
    # A SLOWER router flushing an OLDER observation: dropped.
    assert not r2.record(
        "http://b:1", ejected=False, fails=0, observed_ts=now - 5.0
    )
    assert r2.load()["backends"]["http://b:1"]["ejected"] is True


def test_registry_stale_probe_cannot_resurrect_ejected(tmp_path):
    """The cross-process half of the PR 9 stale-probe guard: recovery
    evidence observed BEFORE the ejection landed cannot re-admit."""
    r1, r2 = _reg(tmp_path, "r1"), _reg(tmp_path, "r2")
    t_eject = time.time()
    r1.record(
        "http://b:1", ejected=True, fails=2, observed_ts=t_eject,
        ejected_at_ts=t_eject,
    )
    # r2's probe STARTED before the ejection: its 200 is stale.
    assert not r2.record(
        "http://b:1", ejected=False, fails=0, observed_ts=t_eject,
    )
    assert r2.load()["backends"]["http://b:1"]["ejected"] is True
    # Genuinely fresh recovery evidence re-admits.
    assert r2.record(
        "http://b:1", ejected=False, fails=0, observed_ts=t_eject + 1.0
    )
    assert r2.load()["backends"]["http://b:1"]["ejected"] is False


def test_registry_lease_breaks_stale_lock(tmp_path):
    r = _reg(tmp_path)
    # A crashed writer left an expired lease behind.
    with open(r.lock_path, "w") as fh:
        json.dump({"writer": "dead", "expires_ts": time.time() - 60}, fh)
    assert r.record("http://b:1", ejected=True, fails=1,
                    observed_ts=time.time())
    assert not os.path.exists(r.lock_path)


def test_registry_survives_corrupt_file(tmp_path):
    r = _reg(tmp_path)
    with open(r.path, "w") as fh:
        fh.write("{not json")
    assert r.load()["backends"] == {}
    assert r.record("http://b:1", ejected=False, fails=0,
                    observed_ts=time.time())


# -- tier-1 smoke: the full multi-process chaos acceptance run ---------------


def test_probe_chaos_smoke():
    """CI satellite: the chaos acceptance probe — 200 requests /
    2 tenants through 2 replicated routers + 2 journal-backed backends
    under the seeded fault schedule (stall, backend kill -9 + restart,
    front-end kill -9 + torn WAL tail + replay, router kill -9,
    graceful drain) — runs on every tier-1 pass under a wall budget,
    asserting zero lost acknowledged requests, zero duplicate solves,
    and zero warm recompiles."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "probe_chaos.py"),
         "--requests", "200", "--budget-s", "240"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    tail = "\n".join(proc.stdout.splitlines()[-30:])
    assert proc.returncode == 0, (
        f"probe_chaos failed (rc={proc.returncode}):\n{tail}\n"
        f"stderr:\n{proc.stderr[-2000:]}"
    )
    assert "PASS" in proc.stdout
