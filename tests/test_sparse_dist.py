"""Row-sharded matrix-free tier tests (ISSUE 19).

Covers the distributed seam of the sparse-iterative backend on the CPU
harness (8 fake devices via conftest): sharded-vs-single-device
equivalence to 1e-8 with storm_s on a 4-way mesh and storm_m on a
2-way mesh (both instances, both widths — the full cross product costs
two more whole-program compiles than the 1-core tier-1 budget allows),
the zero-warm-recompile invariant (re-solving any already-compiled
(bucket, mesh) config adds nothing to the step-program cache), the
per-shard ≈1/N no-ADAᵀ memory guard, the incomplete-LDLᵀ
preconditioner's CG win over Jacobi at an endgame-like diagonal
spread, the auto escalation that rescues the unstructured endgame on
sparse-iterative itself, the host-canonical warm-preconditioner export
surviving a mesh-width change, and the supervisor-facing ``reshard()``
seam. The 2-process launcher equivalence lives in test_multihost.py.
"""

import functools

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from distributedlpsolver_tpu.backends import sparse_iterative as si
from distributedlpsolver_tpu.backends.sparse_iterative import (
    SparseIterativeBackend,
)
from distributedlpsolver_tpu.ipm import driver
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.models.generators import (
    netlib_sparse_lp,
    storm_sparse_lp,
)
from distributedlpsolver_tpu.models.problem import to_interior_form
from distributedlpsolver_tpu.ops import ildl as ildl_ops
from distributedlpsolver_tpu.ops import pcg as pcg_ops
from distributedlpsolver_tpu.ops import sparse as sparse_ops
from distributedlpsolver_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.sparse

# storm_s / storm_m: the same instance family the single-device suite
# uses, small enough for 1-core CI, structured enough that the bordered
# preconditioner engages (the apply round-trip crosses the shard seam).
STORM_S = (6, 24, 36, 24, 3)
STORM_M = (12, 24, 32, 16, 10)


def _mesh(width):
    return mesh_lib.make_mesh(
        (width,), axis_names=("batch",), devices=jax.devices()[:width]
    )


def _storm(spec):
    k, mb, nb, fs, seed = spec
    return storm_sparse_lp(k, mb, nb, fs, seed=seed)


@functools.lru_cache(maxsize=None)
def _single_ref(spec):
    """Single-device reference solve, shared across tests (each extra
    whole-program compile costs ~10 s of 1-core tier-1 wall)."""
    be = SparseIterativeBackend()
    r = driver.solve(_storm(spec), backend=be, tol=1e-8)
    assert r.status.value == "optimal"
    return r


# -- sharded vs single-device equivalence -------------------------------


class TestShardedEquivalence:
    @pytest.mark.parametrize(
        "spec,width",
        [(STORM_S, 4), (STORM_M, 2)],
        ids=["storm_s-4way", "storm_m-2way"],
    )
    def test_matches_single_device_1e8(self, spec, width):
        """Same instance, same tolerance: the row-sharded solve must be
        numerically indistinguishable from the single-device one — the
        psum-reduced normal matvec and the global-apply preconditioner
        round-trip change the schedule, not the math."""
        r_ref = _single_ref(spec)
        be = SparseIterativeBackend(mesh=_mesh(width))
        r = driver.solve(_storm(spec), backend=be, tol=1e-8)
        assert r.status.value == "optimal"
        assert r.iterations == r_ref.iterations
        assert r.objective == pytest.approx(
            r_ref.objective, abs=1e-8 * (1 + abs(r_ref.objective))
        )
        x_ref = np.asarray(r_ref.x)
        dx = np.max(np.abs(np.asarray(r.x) - x_ref))
        assert dx <= 1e-8 * (1 + np.max(np.abs(x_ref))), dx

        rep = be.cg_report()
        assert rep["shards"] == width
        assert rep["psum_per_iter"] == 1

    def test_zero_warm_recompiles_across_widths(self):
        """One SPMD program per (bucket, mesh): re-solving every
        (instance, width) config the equivalence tests above already
        compiled must add ZERO entries to the step-program cache."""
        _single_ref(STORM_S)  # ensure the single-device config is warm
        for spec, width in ((STORM_S, 4), (STORM_M, 2)):
            be = SparseIterativeBackend(mesh=_mesh(width))
            r = driver.solve(_storm(spec), backend=be, tol=1e-8)
            assert r.status.value == "optimal"
        base = si._sparse_step_jit._cache_size()
        for spec, width in ((STORM_S, 4), (STORM_M, 2)):
            be = SparseIterativeBackend(mesh=_mesh(width))
            r = driver.solve(_storm(spec), backend=be, tol=1e-8)
            assert r.status.value == "optimal"
        r = driver.solve(
            _storm(STORM_S), backend=SparseIterativeBackend(), tol=1e-8
        )
        assert r.status.value == "optimal"
        assert si._sparse_step_jit._cache_size() == base

    def test_per_shard_memory_fraction_no_adat(self):
        """The point of sharding: each device holds ≈1/N of the
        operator (row blocks padded to a common count — bounded slack),
        and no operand anywhere approaches the (m, m) ADAᵀ footprint.
        Setup-only: the guard needs placement, not a solve."""
        width = 4
        inf = to_interior_form(_storm(STORM_M))
        cfg = SolverConfig(tol=1e-8)
        # Jacobi pins the comparison to the operator itself — the
        # bordered factors are replicated by design and would mask the
        # 1/N law at toy sizes.
        be1 = SparseIterativeBackend(precond="jacobi")
        be1.setup(inf, cfg)
        beN = SparseIterativeBackend(precond="jacobi", mesh=_mesh(width))
        beN.setup(to_interior_form(_storm(STORM_M)), cfg)

        whole = be1.max_operand_nbytes()
        per_dev = beN.max_operand_nbytes(per_device=True)
        # ≈1/N with slack for the common-row-count padding of the
        # hybrid-ELL blocks and the per-shard transpose-ELL width.
        assert per_dev <= (whole / width) * 1.6, (per_dev, whole)

        m = int(inf.A.shape[0])
        normal_bytes = m * m * 8
        for name, info in beN.memory_report().items():
            # Per-DEVICE view: what one chip actually holds must stay
            # far from ADAᵀ even at this toy size (the 20k slow-tier
            # test asserts the asymptotic 2% bound).
            per = info.get("nbytes_per_device", info["nbytes"])
            assert per < 0.2 * normal_bytes, (name, info)
            shp = info["shape"]
            assert not (len(shp) >= 2 and min(shp[-2:]) >= m), (name, info)

    def test_reshard_returns_fresh_backend(self):
        """Supervisor seam: ``reshard(new_mesh)`` hands back an
        un-setup backend carrying the SAME precond request on the new
        mesh — the driver re-runs setup, the warm cache re-seeds."""
        be = SparseIterativeBackend(mesh=_mesh(2))
        be2 = be.reshard(_mesh(4))
        assert be2 is not be
        assert isinstance(be2, SparseIterativeBackend)
        assert be2._precond_req == "auto"
        assert len(be2.mesh.devices.ravel()) == 4
        r = driver.solve(_storm(STORM_S), backend=be2, tol=1e-8)
        assert r.status.value == "optimal"
        assert be2.cg_report()["shards"] == 4

    def test_sharded_rejects_explicit_ildl(self):
        be = SparseIterativeBackend(precond="ildl", mesh=_mesh(2))
        with pytest.raises(ValueError, match="row-sharded"):
            be.setup(to_interior_form(_storm(STORM_S)), SolverConfig())


# -- incomplete-LDLᵀ preconditioning ------------------------------------


class TestILDL:
    def test_ildl_beats_jacobi_cg(self):
        """At an endgame-like 1e-6 diagonal spread the shifted IC(0)
        factors must buy strictly fewer CG iterations than diagonal
        Jacobi on the SAME normal operator at the SAME forcing
        tolerance."""
        A = netlib_sparse_lp(60, 110, seed=10).A.tocsr()
        m, n = A.shape
        rng = np.random.default_rng(0)
        d = jnp.asarray(10.0 ** rng.uniform(-6.0, 0.0, n))
        reg = jnp.asarray(1e-8, jnp.float64)

        op = sparse_ops.from_scipy(A)

        def mv(v):
            return op.matvec(d * op.rmatvec(v)) + reg * v

        diag = op.normal_diag(d, reg)
        jac = lambda r: r / diag  # noqa: E731
        ild = ildl_ops.ILDLPrecond(A)
        apply_ildl = ild.apply_with(ild.factor(d, reg))

        rhs = jnp.asarray(rng.standard_normal(m))
        cap = 2000
        _, it_jac = pcg_ops.pcg(mv, jac, rhs, 1e-6, cap)
        _, it_ildl = pcg_ops.pcg(mv, apply_ildl, rhs, 1e-6, cap)
        it_jac, it_ildl = int(it_jac), int(it_ildl)
        assert it_ildl < cap
        assert it_ildl < it_jac, (it_ildl, it_jac)

    def test_ildl_escalation_rescues_unstructured_endgame(self):
        """Same family as test_unstructured_endgame_degrades_to_cpu_sparse
        (which pins to jacobi): under precond='auto' the backend detects
        the Jacobi CG degradation streak, escalates to incomplete-LDLᵀ
        mid-solve, and finishes to 1e-8 on sparse-iterative ITSELF —
        no degradation to the host rung. A smaller sibling instance for
        the 1-core tier-1 budget — jacobi alone hits numerical_error on
        it just the same; the full-size (120, 220) escalation is
        recorded in BENCH_SPARSE.json (ildl-vs-jacobi row)."""
        be = SparseIterativeBackend()  # auto
        r = driver.solve(
            netlib_sparse_lp(60, 110, seed=10), backend=be, tol=1e-8
        )
        assert r.status.value == "optimal"
        assert be.precond == "ildl"
        assert be.cg_report()["precond"] == "ildl"


# -- warm preconditioner across mesh widths -----------------------------


class TestWarmAcrossWidths:
    def test_warm_precond_survives_mesh_width_change(self):
        """Mesh-width regression (ISSUE 19 satellite): a warm entry
        written at one width must seed a backend at ANY width — the
        export is host numpy, the factors rebuild on the offeree's own
        placement. Exercised in the reshard-recovery direction (2-way
        mesh → single device)."""
        from distributedlpsolver_tpu.serve.warmcache import WarmCache

        cache = WarmCache(8)
        be_cold = SparseIterativeBackend(mesh=_mesh(2))
        r_cold = driver.solve(
            _storm(STORM_M), backend=be_cold, tol=1e-8, warm_cache=cache
        )
        assert r_cold.status.value == "optimal"
        assert be_cold.cg_report()["warm_precond_steps"] == 0
        exported = be_cold.export_precond()
        assert isinstance(exported, dict)
        assert isinstance(exported["d"], np.ndarray)
        assert exported["d"].dtype == np.float64
        assert exported["precond"] == be_cold.precond

        # Same structure, perturbed c — re-solved at a DIFFERENT width.
        p2 = _storm(STORM_M)
        p2.c = p2.c * 1.01
        be_warm = SparseIterativeBackend()
        r_warm = driver.solve(
            p2, backend=be_warm, tol=1e-8, warm_cache=cache
        )
        assert r_warm.status.value == "optimal"
        assert be_warm.cg_report()["warm_precond_steps"] > 0

    def test_offer_accepts_dict_and_bare_array(self):
        inf = to_interior_form(_storm(STORM_S))
        be = SparseIterativeBackend(mesh=_mesh(2))
        be.setup(inf, SolverConfig(tol=1e-8))
        assert be.offer_precond(np.ones(inf.n))  # legacy bare vector
        assert be.offer_precond(
            {"d": np.ones(inf.n), "precond": "bordered"}
        )
        assert not be.offer_precond({"precond": "bordered"})  # no d
        assert not be.offer_precond({"d": np.ones(inf.n + 1)})
