"""Two-phase (f32→f64) fused solve, stall detection, and phase composition.

The two-phase schedule is the default TPU execution path
(``factor_dtype="auto"``, SURVEY.md §7 mixed-precision design), so its
machinery — ``fused_solve`` stall exits, ``carry_in`` composition,
``buffer_cap`` bucketing, the Pallas pre-pad contract — is tested here on
the CPU test platform: phase 1 runs its plain-XLA f32 assembly branch
(``use_pallas=False``) and the platform gate is monkeypatched, per the
SURVEY.md §4 fake-backend strategy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import core, solve
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMState, Status, StepStats
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.ops import normal_eq_pallas, pad_for_pallas
from tests.oracle import highs_on_general


# ---------------------------------------------------------------- helpers
def _const_stats(rel_gap, pinf=0.0, dinf=0.0, bad=False):
    z = jnp.asarray(0.0, jnp.float64)
    return StepStats(
        mu=jnp.asarray(rel_gap, jnp.float64),
        gap=jnp.asarray(rel_gap, jnp.float64),
        rel_gap=jnp.asarray(rel_gap, jnp.float64),
        pinf=jnp.asarray(pinf, jnp.float64),
        dinf=jnp.asarray(dinf, jnp.float64),
        pobj=z,
        dobj=z,
        alpha_p=z,
        alpha_d=z,
        sigma=z,
        bad=jnp.asarray(bad),
    )


def _tiny_state():
    one = jnp.ones(2, jnp.float64)
    return IPMState(x=one, y=jnp.ones(1, jnp.float64), s=one, w=one, z=one * 0)


_PARAMS = SolverConfig().step_params()
_REG0 = jnp.asarray(1e-10, jnp.float64)


def _run(step, max_iter=50, stall_window=0, carry_in=None, finalize=True):
    return core.fused_solve(
        step,
        _tiny_state(),
        _REG0,
        _PARAMS,
        max_iter,
        5,
        100.0,
        core.buffer_cap(max_iter),
        stall_window=stall_window,
        carry_in=carry_in,
        finalize=finalize,
    )


# ------------------------------------------------------------- buffer_cap
def test_buffer_cap_buckets():
    # One bucket covers every common max_iter (incl. 2 phase budgets of the
    # default 200), so warm-ups share the production compile.
    assert core.buffer_cap(1) == 512
    assert core.buffer_cap(2 * 200) == 512
    assert core.buffer_cap(512) == 512
    assert core.buffer_cap(513) == 1024
    assert core.buffer_cap(1000) == 1024


# ------------------------------------------------- stall exit & finalize
def test_stall_exit_reports_stalled():
    # Error never improves -> with a stall window the loop must stop well
    # before max_iter and report STATUS_STALL (not MAXITER).
    def step(state, reg):
        return state, _const_stats(1e-3)

    _, it, status, _ = _run(step, max_iter=100, stall_window=5)
    assert int(status) == core.STATUS_STALL
    assert int(it) <= 8  # window + the first few establishing best_err


def test_stall_disabled_runs_to_max_iter():
    def step(state, reg):
        return state, _const_stats(1e-3)

    _, it, status, _ = _run(step, max_iter=30, stall_window=0)
    assert int(status) == core.STATUS_MAXITER
    assert int(it) == 30


def test_non_finalize_leaves_running_on_stall():
    def step(state, reg):
        return state, _const_stats(1e-3)

    _, _, status, _ = _run(step, max_iter=100, stall_window=5, finalize=False)
    assert int(status) == core.STATUS_RUNNING


# ------------------------------------------------------ phase composition
def test_carry_in_resumes_iteration_count_and_buffer():
    # Phase A: 3 iterations whose rel_gap halves every step (derived from
    # the state), stopped by max_iter=3 with finalize=False.
    def step_a(state, reg):
        new = state._replace(x=state.x * 0.5)
        return new, _const_stats(1e-3)._replace(rel_gap=jnp.sum(new.x))

    st, it1, status1, buf = _run(step_a, max_iter=3, finalize=False)
    assert int(status1) == core.STATUS_RUNNING
    assert int(it1) == 3
    rows_a = np.asarray(buf)[:3, 2]  # rel_gap column
    assert (rows_a > 0).all()

    # Phase B resumes at iteration 3 and appends to the same buffer.
    def step_b(state, reg):
        return state, _const_stats(0.0)  # instantly optimal

    st2, it2, status2, buf2 = core.fused_solve(
        step_b,
        st,
        _REG0,
        _PARAMS,
        50,
        5,
        100.0,
        core.buffer_cap(50),
        carry_in=(it1, status1, buf),
        finalize=True,
    )
    assert int(status2) == core.STATUS_OPTIMAL
    assert int(it2) == 4  # one phase-B iteration after three phase-A ones
    out = np.asarray(buf2)
    np.testing.assert_allclose(out[:3, 2], rows_a)  # phase-A rows intact
    assert out[3, 2] == 0.0  # phase-B row appended at the global index


def test_carry_in_terminal_status_skips_loop():
    def step(state, reg):  # must never run
        return state, _const_stats(0.0, bad=True)

    st0 = _tiny_state()
    buf0 = jnp.zeros((256, core.N_STAT), jnp.float64)
    _, it, status, _ = core.fused_solve(
        step,
        st0,
        _REG0,
        _PARAMS,
        50,
        5,
        100.0,
        256,
        carry_in=(jnp.asarray(7), jnp.asarray(core.STATUS_OPTIMAL), buf0),
    )
    assert int(status) == core.STATUS_OPTIMAL
    assert int(it) == 7


# ------------------------------------------------- end-to-end two-phase
def test_two_phase_solves_to_full_tol(monkeypatch):
    # Force the platform gate open on CPU; phase 1 then runs the plain-XLA
    # f32 assembly branch (use_pallas=False keeps Pallas out of the way).
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    p = random_dense_lp(30, 80, seed=5)
    be = DenseJaxBackend()
    r = solve(p, backend=be, factor_dtype="auto", use_pallas=False)
    assert be._two_phase
    assert not be._pallas_p1
    assert r.status == Status.OPTIMAL
    assert r.rel_gap <= 1e-8 and r.pinf <= 1e-8 and r.dinf <= 1e-8
    ref = highs_on_general(p)
    np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)
    # the iteration log must cover every iteration exactly once
    assert len(r.history) == r.iterations
    assert [rec.iter for rec in r.history] == list(range(1, r.iterations + 1))
    # per-phase utilization split (drive_phase_plan report): every phase
    # row carries the keys the scale artifacts fold into FLOP/s — mode
    # from the plan spec, never an index guess — and the iteration
    # totals reconcile with the solve
    rep = be.phase_report
    assert rep and all(
        {"phase", "iters", "wall_s", "mode"} <= set(ph) for ph in rep
    )
    assert [ph["mode"] for ph in rep][:1] == ["f32"]
    assert sum(ph["iters"] for ph in rep) == r.iterations


def test_auto_is_single_phase_off_tpu():
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    p = random_dense_lp(10, 24, seed=3)
    be = DenseJaxBackend()
    r = solve(p, backend=be)  # default factor_dtype="auto" on CPU platform
    assert not be._two_phase
    assert be._factor_dtype_name == "float64"
    assert r.status == Status.OPTIMAL


def test_use_pallas_false_respected_in_two_phase(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    from distributedlpsolver_tpu.backends.dense import DenseJaxBackend

    p = random_dense_lp(12, 30, seed=2)
    be = DenseJaxBackend()
    be.setup(
        __import__(
            "distributedlpsolver_tpu.models.problem", fromlist=["to_interior_form"]
        ).to_interior_form(p),
        SolverConfig(use_pallas=False),
    )
    assert be._two_phase and not be._pallas_p1


def test_two_phase_sharded_on_mesh(monkeypatch):
    # The sharded backend runs phase 1 as a GSPMD-partitioned f32 GEMM
    # (Pallas stays off under sharding); exercised on the 8-virtual-device
    # CPU mesh with the platform gate forced open.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    from distributedlpsolver_tpu.backends.sharded import ShardedJaxBackend

    p = random_dense_lp(24, 64, seed=11)
    be = ShardedJaxBackend()
    r = solve(p, backend=be)
    assert be._two_phase and not be._pallas_p1
    assert r.status == Status.OPTIMAL
    assert r.rel_gap <= 1e-8
    ref = highs_on_general(p)
    np.testing.assert_allclose(r.objective, ref.fun, rtol=1e-6, atol=1e-7)


def test_two_phase_batched(monkeypatch):
    # Force the phased schedule despite the tiny members: auto keys it on
    # member size (measured single-phase win at the reference batched
    # shape — see batched._PHASED_MEMBER_ENTRIES), so the all-f32 phase-1
    # path would otherwise never run in CI.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    from distributedlpsolver_tpu.backends import batched as bt
    from distributedlpsolver_tpu.backends.batched import solve_batched
    from distributedlpsolver_tpu.models.generators import random_batched_lp

    monkeypatch.setattr(bt, "_PHASED_MEMBER_ENTRIES", 1)
    batch = random_batched_lp(8, 12, 30, seed=4)
    res = solve_batched(batch, solve_mode="direct")
    # the all-f32 phase must actually have run, then the f64 finish
    assert res.phase_report is not None
    modes = [ph["mode"] for ph in res.phase_report]
    assert modes[0] == "f32-state" and modes[-1] == "float64", modes
    assert res.n_optimal == 8
    assert (res.rel_gap <= 1e-8).all()
    # oracle-check one member
    import scipy.optimize as sopt

    hg = sopt.linprog(
        np.asarray(batch.c[0]),
        A_eq=np.asarray(batch.A[0]),
        b_eq=np.asarray(batch.b[0]),
        bounds=[(0, None)] * batch.A.shape[2],
        method="highs",
    )
    np.testing.assert_allclose(res.objective[0], hg.fun, rtol=1e-6, atol=1e-7)


# --------------------------------------------------- pad_for_pallas contract
def test_pad_for_pallas_roundtrip_matches_reference():
    rng = np.random.default_rng(7)
    m, n = 50, 130
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    d = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    Ap = pad_for_pallas(A, block_m=64, block_k=64)
    assert Ap.shape == (64, 192)
    M = normal_eq_pallas(Ap, d, block_m=64, block_k=64, out_m=m, interpret=True)
    Mr = (A * d[None, :]) @ A.T
    assert M.shape == (m, m)
    np.testing.assert_allclose(np.asarray(M), np.asarray(Mr), rtol=2e-4, atol=1e-4)


def test_pad_for_pallas_aligned_is_identity():
    A = jnp.zeros((64, 128), jnp.float32)
    assert pad_for_pallas(A, block_m=64, block_k=64) is A


def test_out_m_requires_prepadded_matrix():
    A = jnp.zeros((50, 130), jnp.float32)  # NOT tile-aligned
    d = jnp.ones(130, jnp.float32)
    with pytest.raises(ValueError, match="pre-padded"):
        normal_eq_pallas(A, d, block_m=64, block_k=64, out_m=50, interpret=True)


def test_short_d_rejected_without_out_m():
    A = jnp.zeros((64, 128), jnp.float32)
    d = jnp.ones(100, jnp.float32)  # wrong length
    with pytest.raises(ValueError, match="expected"):
        normal_eq_pallas(A, d, block_m=64, block_k=64, interpret=True)


# ------------------------------------------------- tiled f64 ops contract
def test_chunked_ops_match_direct(monkeypatch):
    # Force tiling on small shapes (incl. ragged tails) — at scale these
    # bound XLA's emulated-f64 operand-split temps (see dense._CHUNK_ENTRIES).
    import distributedlpsolver_tpu.backends.dense as dense

    monkeypatch.setattr(dense, "_CHUNK_ENTRIES", 300)
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((37, 53)))
    d = jnp.asarray(rng.random(53) + 0.1)
    v = jnp.asarray(rng.standard_normal(53))
    y = jnp.asarray(rng.standard_normal(37))
    np.testing.assert_allclose(
        np.asarray(dense._normal_eq_chunked(A, d)),
        np.asarray((A * d[None, :]) @ A.T), rtol=1e-12, atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(dense._matvec_chunked(A, v)), np.asarray(A @ v),
        rtol=1e-12, atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(dense._rmatvec_chunked(A, y)), np.asarray(A.T @ y),
        rtol=1e-12, atol=1e-12,
    )


def test_chunked_ops_tiny_m(monkeypatch):
    # m smaller than the 8-row tile floor must not produce oversized slices.
    import distributedlpsolver_tpu.backends.dense as dense

    monkeypatch.setattr(dense, "_CHUNK_ENTRIES", 20)
    rng = np.random.default_rng(1)
    A = jnp.asarray(rng.standard_normal((3, 40)))
    d = jnp.asarray(rng.random(40) + 0.1)
    np.testing.assert_allclose(
        np.asarray(dense._normal_eq_chunked(A, d)),
        np.asarray((A * d[None, :]) @ A.T), rtol=1e-12, atol=1e-12,
    )


def test_solve_end_to_end_with_forced_tiling(monkeypatch):
    import distributedlpsolver_tpu.backends.dense as dense

    monkeypatch.setattr(dense, "_CHUNK_ENTRIES", 500)
    p = random_dense_lp(20, 50, seed=9)
    r = solve(p, backend="tpu")  # dense JAX backend on the CPU platform
    assert r.status == Status.OPTIMAL and r.rel_gap <= 1e-8
