"""Auxiliary subsystems (SURVEY.md §5): CLI surface, checkpoint/resume,
warm start, and the JSONL metrics stream."""

import json
import os

import numpy as np
import pytest

from distributedlpsolver_tpu.cli import main as cli_main
from distributedlpsolver_tpu.io import write_mps
from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.ipm.state import IPMState, Status
from distributedlpsolver_tpu.models.generators import random_general_lp
from distributedlpsolver_tpu.utils import checkpoint as ckpt


@pytest.fixture
def mps_file(tmp_path):
    p = random_general_lp(10, 24, seed=21)
    path = str(tmp_path / "prob.mps")
    write_mps(p, path)
    return path, p


# ------------------------------------------------------------------- CLI
def test_cli_solve_json(mps_file, capsys):
    path, _ = mps_file
    rc = cli_main(["solve", path, "--backend=cpu", "--quiet", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] == "optimal"
    assert out["rel_gap"] <= 1e-8
    assert out["backend"] == "cpu"


def test_cli_solve_writes_solution(mps_file, tmp_path, capsys):
    path, p = mps_file
    x_out = str(tmp_path / "x.npy")
    rc = cli_main(["solve", path, "--backend=cpu", "--quiet", "--x-out", x_out])
    assert rc == 0
    x = np.load(x_out)
    assert x.shape == (p.n,)
    assert p.max_violation(x) <= 1e-6


def test_cli_backends_lists_registry(capsys):
    assert cli_main(["backends"]) == 0
    names = capsys.readouterr().out.split()
    for expected in ("tpu", "cpu", "cpu-native", "cpu-sparse", "sharded", "block"):
        assert expected in names


def test_cli_generate_round_trips(tmp_path, capsys):
    out = str(tmp_path / "gen.mps")
    rc = cli_main(["generate", "block", out, "--m", "8", "--n", "20", "--blocks", "2",
                   "--link", "4"])
    assert rc == 0
    rc = cli_main(["solve", out, "--backend=cpu", "--quiet", "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["status"] == "optimal"


def test_cli_nonoptimal_exit_code(tmp_path, capsys):
    # An infeasible problem must exit 2, not 0 (scripting contract).
    from distributedlpsolver_tpu.models.problem import LPProblem

    p = LPProblem(
        c=[1.0, 1.0], A=[[1.0, 1.0], [1.0, 1.0]],
        rlb=[2.0, -np.inf], rub=[2.0, 1.0],
        lb=[0.0, 0.0], ub=[np.inf, np.inf], name="infeas",
    )
    path = str(tmp_path / "infeas.mps")
    write_mps(p, path)
    rc = cli_main(["solve", path, "--backend=cpu", "--quiet", "--json"])
    assert rc == 2
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] in ("primal_infeasible", "numerical_error")


# -------------------------------------------------- checkpoint / restart
def test_checkpoint_save_load_round_trip(tmp_path):
    path = str(tmp_path / "ck.npz")
    state = IPMState(
        x=np.arange(4.0), y=np.ones(2), s=np.full(4, 2.0),
        w=np.ones(4), z=np.zeros(4),
    )
    ckpt.save_state(path, state, 17, "prob")
    loaded, it, name = ckpt.load_state(path)
    assert (it, name) == (17, "prob")
    for f in IPMState._fields:
        np.testing.assert_array_equal(getattr(loaded, f), getattr(state, f))
    assert ckpt.maybe_load(None) is None
    assert ckpt.maybe_load(str(tmp_path / "missing.npz")) is None


def test_solve_resumes_from_checkpoint(tmp_path):
    p = random_general_lp(10, 24, seed=5)
    ck = str(tmp_path / "it.npz")
    # Interrupted run: checkpoint every iteration, stop early.
    r1 = solve(p, backend="cpu", checkpoint_path=ck, checkpoint_every=1, max_iter=4)
    assert r1.status == Status.ITERATION_LIMIT
    assert os.path.exists(ck)
    # Resumed run finds the checkpoint and needs fewer iterations than a
    # cold solve to reach optimality.
    cold = solve(p, backend="cpu")
    r2 = solve(p, backend="cpu", checkpoint_path=ck, checkpoint_every=1)
    assert r2.status == Status.OPTIMAL
    assert r2.iterations < cold.iterations
    np.testing.assert_allclose(r2.objective, cold.objective, rtol=1e-7, atol=1e-8)


def test_warm_start_accepts_prior_state(tmp_path):
    # The checkpoint payload is the documented warm-start carrier.
    p = random_general_lp(8, 18, seed=6)
    ck = str(tmp_path / "ws.npz")
    solve(p, backend="cpu", checkpoint_path=ck, checkpoint_every=1, max_iter=6)
    state, _, _ = ckpt.load_state(ck)
    r2 = solve(p, backend="cpu", warm_start=state)
    assert r2.status == Status.OPTIMAL


# ------------------------------------------------------------ JSONL logs
def test_jsonl_iteration_log(tmp_path):
    p = random_general_lp(10, 24, seed=7)
    log = str(tmp_path / "iters.jsonl")
    r = solve(p, backend="cpu", log_jsonl=log)
    assert r.status == Status.OPTIMAL
    records = [json.loads(line) for line in open(log)]
    assert len(records) == r.iterations
    assert [rec["iter"] for rec in records] == list(range(1, r.iterations + 1))
    for key in ("mu", "gap", "rel_gap", "pinf", "dinf", "alpha_p", "alpha_d",
                "sigma", "pobj", "dobj", "t_iter"):
        assert key in records[0]
    # the trajectory the metric surface promises: gap decreases to tol
    assert records[-1]["rel_gap"] <= 1e-8


def test_compile_cache_configured_by_default():
    # Package import points the persistent XLA compilation cache somewhere
    # (the emulated-f64 batched programs take minutes to compile, ~1 s to
    # run — caching makes every later process start warm). Environments
    # that opt out or pre-configure their own dir are respected, so only
    # the default case is asserted.
    import os

    import jax

    import distributedlpsolver_tpu  # noqa: F401

    if os.environ.get("TPULP_NO_COMPILE_CACHE"):
        pytest.skip("cache explicitly disabled in this environment")
    d = jax.config.jax_compilation_cache_dir
    custom = os.environ.get("TPULP_COMPILE_CACHE")
    if custom:
        assert d == custom
    else:
        assert d  # configured to SOME persistent location


def test_profile_dir_always_yields_a_report(tmp_path):
    """--profile-dir honesty: whatever jax.profiler.trace does (it writes
    nothing through tunneled TPUs), the profile dir must come back with
    the dispatch-level timing report, one entry per iteration."""
    prof = tmp_path / "prof"
    p = random_general_lp(8, 18, seed=4)
    r = solve(p, backend="cpu", profile_dir=str(prof), verbose=False)
    assert r.status == Status.OPTIMAL
    report = json.loads((prof / "dispatch_timings.json").read_text())
    assert report["iterations"] == r.iterations > 0
    assert len(report["t_iter_s"]) == r.iterations
    assert report["solve_s"] > 0
    assert "jax_profiler_trace_wrote_files" in report


def test_profile_dir_forces_host_loop(tmp_path):
    """The fused on-device loop has no iteration boundaries to profile;
    profile_dir must force the per-iteration host driver (else the trace
    wraps nothing and the report has no rows)."""
    prof = tmp_path / "prof2"
    p = random_general_lp(8, 18, seed=4)
    r = solve(
        p, backend="cpu", profile_dir=str(prof), fused_loop=None,
        verbose=False,
    )
    report = json.loads((prof / "dispatch_timings.json").read_text())
    # host loop ran: true per-iteration wall times, not one fused average
    assert len(set(report["t_iter_s"])) > 1 or r.iterations <= 1
