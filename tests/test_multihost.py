"""Multi-host world runtime tests (ISSUE 13 acceptance).

Real ``jax.distributed`` worlds — N spawned CPU processes × K virtual
devices each, gloo cross-process collectives — driven through
distributed/launcher. The CPU harness maps 1:1 onto TPU pod slices:
everything above the launcher env contract is identical there.

Budgeted for tier-1: tiny shapes (process startup and compiles dominate,
not solving), one shared world per check where possible, and the
launcher's one-retry tolerance for the harness transport's best-effort
failure mode (a transport flake kills a world by design; relaunching IS
the recovery model).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributedlpsolver_tpu.distributed.launcher import (
    SupervisorConfig,
    WorldSupervisor,
    run_world,
    worker_argv,
)

pytestmark = pytest.mark.multihost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _single_process_reference(m, n, seed, tol=1e-8):
    from distributedlpsolver_tpu.ipm import solve
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.models.generators import random_dense_lp

    p = random_dense_lp(m, n, seed=seed)
    return solve(
        p, backend="dense", config=SolverConfig(tol=tol, verbose=False)
    )


def test_sharded_solve_matches_single_process(tmp_path):
    """The acceptance equivalence: 2- and 4-process sharded solves
    (variable axis spanning every device of every process, Schur
    all-reduce over the process boundary) match the single-process
    solve to 1e-8. One reference solve serves both worlds."""
    m, n, seed = 32, 96, 5
    ref = _single_process_reference(m, n, seed)
    assert ref.status.value == "optimal"
    for world_size in (2, 4):
        res = run_world(
            "sharded_solve",
            {"m": m, "n": n, "seed": seed, "tol": 1e-8},
            world_size=world_size,
            workdir=str(tmp_path / f"w{world_size}"),
            local_devices=2,
            timeout=240,
        )
        assert set(res) == set(range(world_size))
        for rank, out in res.items():
            assert out["status"] == "optimal", (rank, out)
            assert out["world_size"] == world_size
            assert out["global_devices"] == 2 * world_size
            rel = abs(out["objective"] - ref.objective) / max(
                1.0, abs(ref.objective)
            )
            assert rel <= 1e-8, (rank, out["objective"], ref.objective)
        # Every rank ran the SAME SPMD program: identical iterations.
        iters = {out["iterations"] for out in res.values()}
        assert len(iters) == 1


def test_bucket_zero_warm_recompile_across_processes(tmp_path):
    """Serving fast path over a 4-process global mesh: second dispatch
    of a warm bucket compiles NOTHING on any rank, and the program-cache
    size agrees world-wide (the rank-0-gather agreement check)."""
    res = run_world(
        "bucket_probe",
        {"m": 8, "n": 24, "batch": 8, "tol": 1e-8},
        world_size=4,
        workdir=str(tmp_path / "bw"),
        local_devices=2,
        timeout=240,
    )
    # Cross-check the multi-process bucket objectives against a
    # single-process solve of the same seeded batch.
    from distributedlpsolver_tpu.backends.batched import solve_bucket
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.models.generators import random_batched_lp

    batch = random_batched_lp(8, 8, 24, seed=7)
    local = solve_bucket(
        batch,
        np.ones(8, dtype=bool),
        SolverConfig(tol=1e-8, verbose=False),
    )
    for rank, out in res.items():
        assert out["warm_recompiles"] == 0, (rank, out)
        sizes = out["bucket_cache_sizes"]
        assert len(set(sizes)) == 1, sizes  # world-wide agreement
        np.testing.assert_allclose(
            out["objectives_first"], local.objective, rtol=1e-8, atol=1e-10
        )


def test_rank_kill_world_reinit_checkpoint_resume(tmp_path):
    """Coordinator-level recovery: SIGKILL one rank mid-solve — the
    world dies as a unit — and the supervisor re-initializes a SMALLER
    world whose solve resumes from the checkpoint-v3 file and finishes
    OPTIMAL at the reference objective. The world_reinit event carries
    recovery_overhead_s."""
    m, n, seed = 32, 96, 11
    ref = _single_process_reference(m, n, seed)
    workdir = str(tmp_path / "sup")
    ckpt = str(tmp_path / "state.ckpt.npz")
    spec = {
        "m": m, "n": n, "seed": seed, "tol": 1e-8,
        "checkpoint": ckpt, "checkpoint_every": 2,
    }
    out_dir = os.path.join(workdir, "out")

    def argv_for_gen(generation, world_size, port):
        return worker_argv("sharded_solve", spec, out_dir)

    sup = WorldSupervisor(
        argv_for_gen,
        world_size=3,
        workdir=workdir,
        local_devices=2,
        config=SupervisorConfig(
            min_world=1,
            max_reforms=2,
            log_jsonl=os.path.join(workdir, "world.jsonl"),
        ),
    )
    box = {}

    def _run():
        try:
            box["results"] = sup.run(timeout=300)
        except Exception as e:  # surfaced by the main thread's asserts
            box["error"] = e

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    # Wait for the first checkpoint, then kill rank 1 via its heartbeat
    # pid (the authoritative pid record). Read the LATEST generation's
    # heartbeat: should the harness transport have already cost a world
    # (launcher relaunches by design), the stale gen's pid is dead.
    def _latest_hb(rank):
        gens = sorted(
            (d for d in os.listdir(workdir) if d.startswith("hb-gen")),
            key=lambda d: int(d[6:]),
        )
        for d in reversed(gens):
            p = os.path.join(workdir, d, f"rank{rank}.hb")
            if os.path.exists(p):
                return p
        return None

    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if os.path.exists(ckpt) and _latest_hb(1):
            break
        time.sleep(0.1)
    assert os.path.exists(ckpt), "no checkpoint appeared before budget"
    killed = False
    while time.monotonic() < deadline and not killed:
        hb = _latest_hb(1)
        try:
            os.kill(json.load(open(hb))["pid"], signal.SIGKILL)
            killed = True
        except (ProcessLookupError, OSError, ValueError):
            time.sleep(0.2)
    assert killed, "could not kill a live rank-1 process"
    t.join(timeout=300)
    assert not t.is_alive(), "supervision did not finish in budget"
    assert "error" not in box, box.get("error")
    results = box["results"]
    # The completing generation is a 2-process world (3 - 1 lost).
    assert results, "no results from the completing world"
    for rank, out in results.items():
        assert out["status"] == "optimal", (rank, out)
        assert out["world_size"] == 2
        rel = abs(out["objective"] - ref.objective) / max(
            1.0, abs(ref.objective)
        )
        assert rel <= 1e-8
    assert sup.reinit_events, "no world_reinit event emitted"
    assert all(
        e["event"] == "world_reinit" and e["recovery_overhead_s"] >= 0.0
        for e in sup.reinit_events
    )
    # Our kill produced the shrink-to-2 re-initialization (a transport
    # flake may add same-size relaunches around it).
    assert any(e["world_size"] == 2 for e in sup.reinit_events)
    # And the event stream is stamped JSONL on disk.
    lines = [
        json.loads(line)
        for line in open(os.path.join(workdir, "world.jsonl"))
    ]
    assert any(
        r.get("event") == "world_reinit" and "recovery_overhead_s" in r
        for r in lines
    )


def test_registry_heartbeat_ttl_ejects(tmp_path):
    """Registry satellite: a self-registered backend whose heartbeats
    stop is ejected deterministically at the TTL (registry_expired_total
    counts it) even though no probe ever failed — and the stale-probe
    guard rules still hold for the push."""
    from distributedlpsolver_tpu.net.registry import BackendRegistry
    from distributedlpsolver_tpu.net.router import Router, RouterConfig
    from distributedlpsolver_tpu.obs.metrics import MetricsRegistry

    path = str(tmp_path / "reg.json")
    reg = BackendRegistry(path)
    url = "http://127.0.0.1:1"  # nothing listens: probes would fail too
    assert reg.register(url, slice_id="sX", world_size=2)
    assert reg.heartbeat(url)
    doc = reg.load()
    entry = doc["backends"][url]
    assert entry["slice_id"] == "sX"
    assert entry["world_size"] == 2
    assert entry["last_heartbeat_ts"] > 0

    metrics = MetricsRegistry()
    router = Router(
        [],
        RouterConfig(
            registry_path=path,
            registry_ttl_s=0.4,
            eject_after=100,  # probes alone must NOT eject in this test
        ),
        metrics=metrics,
    )
    # Adopted from the registry with no manual config.
    assert url in {b["url"] for b in router.statusz()["backends"]}
    router._sync_registry_pull()
    router._expire_stale_heartbeats()
    st = next(b for b in router.statusz()["backends"] if b["url"] == url)
    assert not st["ejected"]  # heartbeat still fresh
    time.sleep(0.6)
    router._expire_stale_heartbeats()
    st = next(b for b in router.statusz()["backends"] if b["url"] == url)
    assert st["ejected"], "stale heartbeat did not eject"
    snap = metrics.snapshot()
    assert snap.get("registry_expired_total") == 1
    # The ejection was pushed to the shared registry (siblings honor it).
    doc = reg.load()
    assert doc["backends"][url]["ejected"] is True
    # A fresh heartbeat alone must NOT resurrect it (resurrection rule:
    # only a successful probe after the ejection re-admits).
    assert reg.heartbeat(url)
    router._sync_registry_pull()
    st = next(b for b in router.statusz()["backends"] if b["url"] == url)
    assert st["ejected"]


def test_registry_heartbeat_ttl_is_skew_immune(tmp_path):
    """Regression: TTL aging runs on OBSERVER-LOCAL receipt time of
    each beat, never on the serving host's wall-clock stamp. A backend
    whose clock is hours behind keeps beating (each stamp newer than
    the last) and must stay in rotation past the TTL; once the beats
    stop, it ages out at the TTL like anyone else."""
    from distributedlpsolver_tpu.net.registry import BackendRegistry
    from distributedlpsolver_tpu.net.router import Router, RouterConfig
    from distributedlpsolver_tpu.obs.metrics import MetricsRegistry

    path = str(tmp_path / "reg.json")
    reg = BackendRegistry(path)
    url = "http://127.0.0.1:1"
    assert reg.register(url, slice_id="sZ", world_size=2)
    skew_base = time.time() - 7200.0  # two hours behind

    def beat(k):
        # Skewed but monotonic stamps — what a wrong-clock host writes.
        def _mutate(backends):
            backends[url]["last_heartbeat_ts"] = skew_base + 0.001 * k
            return True

        assert reg.update(_mutate) is not None

    beat(0)
    metrics = MetricsRegistry()
    router = Router(
        [],
        RouterConfig(
            registry_path=path,
            registry_ttl_s=0.4,
            eject_after=100,  # probes alone must NOT eject here
        ),
        metrics=metrics,
    )
    # Beats keep arriving: total elapsed exceeds the TTL several times
    # over, yet the entry stays in rotation — wall-skew alone (every
    # stamp is ~2h stale) can never eject a live backend.
    for k in range(1, 5):
        beat(k)
        router._sync_registry_pull()
        router._expire_stale_heartbeats()
        st = next(
            b for b in router.statusz()["backends"] if b["url"] == url
        )
        assert not st["ejected"], f"skewed-but-live backend ejected at beat {k}"
        time.sleep(0.15)
    # The beats stop: observer-local receipt time ages past the TTL and
    # the entry leaves rotation deterministically.
    time.sleep(0.6)
    router._sync_registry_pull()
    router._expire_stale_heartbeats()
    st = next(b for b in router.statusz()["backends"] if b["url"] == url)
    assert st["ejected"], "dead backend with skewed stamps never aged out"
    assert metrics.snapshot().get("registry_expired_total") == 1


def test_record_preserves_slice_fields(tmp_path):
    """A router observation push must not wipe the serving-side fields
    (slice_id / world_size / last_heartbeat_ts)."""
    from distributedlpsolver_tpu.net.registry import BackendRegistry

    path = str(tmp_path / "reg.json")
    reg = BackendRegistry(path)
    url = "http://127.0.0.1:2"
    reg.register(url, slice_id="sY", world_size=4)
    assert reg.record(
        url, ejected=True, fails=3, observed_ts=time.time() + 1
    )
    entry = reg.load()["backends"][url]
    assert entry["ejected"] is True
    assert entry["slice_id"] == "sY"
    assert entry["world_size"] == 4
    assert entry["last_heartbeat_ts"] > 0


@pytest.mark.slow
def test_sparse_rows_matches_single_process(tmp_path):
    """ISSUE 19 acceptance seam: the row-sharded matrix-free backend
    over a 2-process world (hybrid-ELL row blocks per rank, the
    normal-matvec n-vector psum crossing the process boundary) matches
    the single-process sparse-iterative solve to 1e-8, with the
    per-device operand footprint reported per rank.

    Slow tier (PR 17 budget-rebalance precedent): ~60 s of 1-core wall
    — two worker processes each compile their own SPMD programs. The
    tier-1-asserted equivalence acceptance is the single-process mesh
    family in test_sparse_dist.py; run `-m multihost` or `-m slow` to
    execute the cross-process leg."""
    from distributedlpsolver_tpu.backends.sparse_iterative import (
        SparseIterativeBackend,
    )
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.ipm.driver import solve
    from distributedlpsolver_tpu.models.generators import storm_sparse_lp

    spec = {"scenarios": 6, "block_m": 24, "block_n": 36,
            "first_stage_n": 24, "seed": 3, "tol": 1e-8}
    p = storm_sparse_lp(6, block_m=24, block_n=36, first_stage_n=24, seed=3)
    be = SparseIterativeBackend()
    ref = solve(p, backend=be, config=SolverConfig(tol=1e-8, verbose=False))
    assert ref.status.value == "optimal"

    res = run_world(
        "sparse_rows",
        spec,
        world_size=2,
        workdir=str(tmp_path / "w2"),
        local_devices=2,
        timeout=240,
    )
    assert set(res) == {0, 1}
    for rank, out in res.items():
        assert out["status"] == "optimal", (rank, out)
        assert out["shards"] == 4  # 2 procs × 2 local devices
        assert out["psum_per_iter"] == 1
        rel = abs(out["objective"] - ref.objective) / max(
            1.0, abs(ref.objective)
        )
        assert rel <= 1e-8, (rank, out["objective"], ref.objective)
    # One SPMD program world-wide: identical IPM and CG iteration counts.
    assert len({out["iterations"] for out in res.values()}) == 1
    assert len({out["cg_iters"] for out in res.values()}) == 1


def test_block_angular_ragged_tail(tmp_path):
    """Block-angular shrink satellite: K blocks NOT divisible by the
    mesh axis re-shard onto the ragged-tail (dead-block-padded) layout
    and match the unsharded solve to 1e-8 — including a shrunk
    'survivor' width."""
    import jax

    from distributedlpsolver_tpu.backends.block_angular import (
        BlockAngularBackend,
    )
    from distributedlpsolver_tpu.ipm.config import SolverConfig
    from distributedlpsolver_tpu.ipm.driver import solve
    from distributedlpsolver_tpu.models.generators import block_angular_lp
    from distributedlpsolver_tpu.parallel import mesh as mesh_lib

    p = block_angular_lp(5, 12, 30, 8, seed=3)  # K=5: indivisible by 4, 3
    cfg = SolverConfig(tol=1e-8, verbose=False)
    ref = solve(p, backend="block", config=cfg)
    assert ref.status.value == "optimal"
    for width in (4, 3):
        mesh = mesh_lib.make_mesh(
            (width,), axis_names=("blocks",),
            devices=jax.devices()[:width],
        )
        be = BlockAngularBackend(mesh=mesh)
        res = solve(p, backend=be, config=cfg)
        assert res.status.value == "optimal"
        rel = abs(res.objective - ref.objective) / max(
            1.0, abs(ref.objective)
        )
        assert rel <= 1e-8, (width, res.objective, ref.objective)
        # The reshard seam the SHRINK rung uses.
        be2 = be.reshard(
            mesh_lib.make_mesh(
                (2,), axis_names=("blocks",), devices=jax.devices()[:2]
            )
        )
        assert isinstance(be2, BlockAngularBackend)


def test_probe_devices_skips_non_addressable():
    """runtime satellite: probes never ping devices another process
    owns — they land in NEITHER list (no evidence), instead of a
    device_put into a collective nobody else runs."""
    import jax

    from distributedlpsolver_tpu.parallel import runtime as rt

    class _Remote:
        id = 9999
        process_index = jax.process_index() + 1

    healthy, unhealthy = rt.probe_devices(
        [jax.local_devices()[0], _Remote()], deadline=5.0
    )
    assert jax.local_devices()[0] in healthy
    assert all(getattr(d, "id", None) != 9999 for d in healthy + unhealthy)


def test_probe_multihost_smoke(tmp_path):
    """The router-over-2-slices acceptance probe: one slice killed
    mid-run, world re-init, zero lost acks, poll URLs honest, zero
    warm recompiles (scripts/probe_multihost.py)."""
    env = dict(os.environ)
    # One retry: the harness transport (gloo over localhost TCP) is
    # best-effort — a transient pairing failure kills a world by
    # design, and relaunching IS the recovery model (the same contract
    # run_world gives the equivalence tests).
    last = None
    for _ in range(2):
        res = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "probe_multihost.py"),
                "--requests", "18",
                "--budget-s", "300",
            ],
            capture_output=True,
            text=True,
            timeout=330,
            env=env,
            cwd=REPO,
        )
        last = res
        if res.returncode == 0:
            break
    assert last.returncode == 0, (
        f"probe_multihost failed:\n{last.stdout[-4000:]}\n{last.stderr[-2000:]}"
    )
