"""Tests for LPProblem and the interior-form conversion.

Strategy (SURVEY.md §4): the conversion must preserve the feasible set and
objective values — checked by mapping feasible points both ways and by
comparing optimal values via the scipy HiGHS oracle on the converted form.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.models import (
    LPProblem,
    random_dense_lp,
    random_general_lp,
    to_interior_form,
)
from tests.oracle import highs_on_general, highs_on_interior


class TestLPProblem:
    def test_validation(self):
        with pytest.raises(ValueError):
            LPProblem(
                c=[1.0], A=np.ones((1, 1)), rlb=[2.0], rub=[1.0],
                lb=[0.0], ub=[1.0],
            )
        with pytest.raises(ValueError):
            LPProblem(
                c=[1.0, 2.0], A=np.ones((1, 1)), rlb=[1.0], rub=[1.0],
                lb=[0.0], ub=[1.0],
            )

    def test_max_violation(self):
        p = random_dense_lp(5, 9, seed=3)
        # b was built as A @ x0 with x0 in [0.5, 2]; recover such a point:
        x_feas = np.linalg.lstsq(p.A, p.rlb, rcond=None)[0]
        # lstsq point may violate x>=0; just check the metric is consistent
        v = p.max_violation(x_feas)
        assert v >= 0.0
        assert p.max_violation(np.full(p.n, -1.0)) >= 1.0  # violates lb=0


class TestInteriorForm:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("sparse_A", [False, True])
    def test_general_conversion_matches_highs(self, seed, sparse_A):
        p = random_general_lp(12, 20, seed=seed)
        if sparse_A:
            p = LPProblem(
                c=p.c, A=sp.csr_matrix(p.A), rlb=p.rlb, rub=p.rub,
                lb=p.lb, ub=p.ub, c0=p.c0, name=p.name,
            )
        inf = to_interior_form(p)

        res_orig = highs_on_general(p)
        res_int = highs_on_interior(inf)
        assert res_orig.status == 0, res_orig.message
        assert res_int.status == 0, res_int.message
        # Optimal values agree (conversion preserves the problem).
        assert res_int.fun + inf.c0 == pytest.approx(res_orig.fun + p.c0, abs=1e-6)

        # Recovered solution is feasible and optimal for the original.
        x = inf.recover(res_int.x)
        assert p.max_violation(x) < 1e-6
        assert p.objective(x) == pytest.approx(res_orig.fun + p.c0, abs=1e-6)

    def test_standard_form_is_identity_like(self):
        p = random_dense_lp(6, 10, seed=0)
        inf = to_interior_form(p)
        # already min c'x, Ax=b, x>=0: no slacks, no shifts, no splits
        assert inf.n == p.n
        assert inf.m == p.m
        np.testing.assert_allclose(np.asarray(inf.A), np.asarray(p.A))
        np.testing.assert_allclose(inf.b, p.rlb)
        np.testing.assert_allclose(inf.c, p.c)
        assert not inf.has_ub.any()

    def test_recover_roundtrip_feasible_point(self):
        # A feasible point of the interior form must recover to a feasible
        # point of the original with the same objective.
        p = random_general_lp(10, 16, seed=7)
        inf = to_interior_form(p)
        res = highs_on_interior(inf)
        assert res.status == 0
        x = inf.recover(res.x)
        assert p.max_violation(x) < 1e-7
        assert inf.objective(res.x) == pytest.approx(p.objective(x), abs=1e-8)

    def test_upper_bounds_become_u(self):
        n = 4
        p = LPProblem(
            c=np.ones(n),
            A=np.eye(4)[:2],
            rlb=np.array([1.0, -np.inf]),
            rub=np.array([1.0, 5.0]),
            lb=np.array([0.0, -1.0, -np.inf, -np.inf]),
            ub=np.array([2.0, 3.0, 4.0, np.inf]),
        )
        inf = to_interior_form(p)
        # col0: [0,2] -> u=2 ; col1: [-1,3] shift -> u=4 ;
        # col2: (-inf,4] negate -> u=inf... negated+shift(-4) -> u=inf
        # col3: free -> split, both unbounded ; slack row2: (-inf,5]->u=inf? no:
        # slack bounds are [rlb,rub]=(-inf,5] -> negated, u=inf
        assert inf.u[0] == 2.0
        assert inf.u[1] == 4.0
        assert np.isinf(inf.u[2])
        # split adds one extra column for col3
        assert inf.n == n + 1 + 1  # 4 orig + 1 slack + 1 free split
