"""Supervisor recovery-ladder tests via deterministic fault injection.

Every recovery path runs end-to-end on CPU (ISSUE acceptance): hang →
watchdog timeout → retry; NaN → rollback → re-center → OPTIMAL; persistent
backend crash → degradation chain → OPTIMAL on the fallback; retries
exhausted → structured SolveFailure with the ordered fault history. No
test waits out an injected hang — the watchdog deadline bounds every wait.
"""

import time

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.supervisor import (
    FaultKind,
    InjectedCrash,
    InjectedFault,
    SolveFailure,
    StepDeadlineExceeded,
    SupervisorConfig,
    run_with_deadline,
    supervised_solve,
)

pytestmark = pytest.mark.faults

# Small, strictly feasible+bounded by construction: ~12 IPM iterations on
# any backend, so injection iterations 1-5 always exist.
_PROBLEM = dict(m=20, n=45, seed=3)


def _problem():
    return random_dense_lp(**_PROBLEM)


def _sup(**kw):
    kw.setdefault("backoff_base", 0.001)
    return SupervisorConfig(**kw)


@pytest.fixture(scope="module")
def reference_result():
    return solve(_problem(), backend="cpu", fused_loop=False)


# ----------------------------------------------------------- watchdog unit
class TestWatchdog:
    def test_passthrough_value(self):
        assert run_with_deadline(lambda: 42, 5.0) == 42

    def test_disabled_timeout_direct_call(self):
        assert run_with_deadline(lambda: "x", None) == "x"
        assert run_with_deadline(lambda: "x", 0) == "x"

    def test_exception_reraises_on_caller(self):
        with pytest.raises(ValueError, match="boom"):
            run_with_deadline(lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0)

    def test_deadline_fires_within_2x(self):
        deadline = 0.2
        t0 = time.perf_counter()
        with pytest.raises(StepDeadlineExceeded):
            run_with_deadline(lambda: time.sleep(10 * deadline), deadline, iteration=7)
        assert time.perf_counter() - t0 < 2 * deadline


# --------------------------------------------------------- recovery paths
def test_no_faults_is_passthrough(reference_result):
    r = supervised_solve(_problem(), backend="cpu", supervisor=_sup())
    assert r.status == Status.OPTIMAL
    assert r.faults == []
    np.testing.assert_allclose(
        r.objective, reference_result.objective, rtol=1e-8
    )


def test_nan_iterate_rolls_back_to_optimal(reference_result):
    plan = [InjectedFault(FaultKind.NUMERICAL, iteration=5)]
    r = supervised_solve(
        _problem(), backend="cpu", supervisor=_sup(fault_plan=plan)
    )
    assert r.status == Status.OPTIMAL
    assert [f.kind for f in r.faults] == [FaultKind.NUMERICAL]
    assert r.faults[0].iteration == 5
    assert r.faults[0].action == "rollback"
    np.testing.assert_allclose(
        r.objective, reference_result.objective, rtol=1e-6
    )


def test_nan_escalates_through_recenter():
    """Three NaNs at the same iteration walk the full per-backend ladder:
    rollback, then reg bump, then re-center — and still reach OPTIMAL."""
    plan = [InjectedFault(FaultKind.NUMERICAL, iteration=4, times=3)]
    r = supervised_solve(
        _problem(), backend="cpu", supervisor=_sup(fault_plan=plan)
    )
    assert r.status == Status.OPTIMAL
    assert [f.action for f in r.faults] == [
        "rollback",
        "rollback+reg_bump",
        "recenter",
    ]


def test_hang_watchdog_timeout_then_retry(reference_result):
    deadline = 0.25
    plan = [
        InjectedFault(FaultKind.HANG, iteration=3, hang_seconds=20 * deadline)
    ]
    t0 = time.perf_counter()
    r = supervised_solve(
        _problem(),
        backend="cpu",
        supervisor=_sup(fault_plan=plan, step_timeout=deadline),
    )
    elapsed = time.perf_counter() - t0
    assert r.status == Status.OPTIMAL
    assert [f.kind for f in r.faults] == [FaultKind.HANG]
    assert r.faults[0].iteration == 3
    # The watchdog abandoned the hang instead of waiting it out: total
    # wall time is far below the injected 5 s hang.
    assert elapsed < 10 * deadline
    np.testing.assert_allclose(
        r.objective, reference_result.objective, rtol=1e-6
    )


@pytest.mark.slow
def test_persistent_crash_degrades_backend(reference_result):
    """A backend that crashes every attempt climbs the ladder, then the
    supervisor degrades along backends.auto.DEGRADATION_CHAIN and the
    fallback backend finishes the solve.

    Slow tier (PR 17 budget-rebalance precedent): ~10 s of 1-core wall
    for the full every-attempt crash ladder. Ladder exhaustion,
    watchdog retry, and degradation itself stay tier-1 via
    test_retries_exhausted_raises_structured_failure,
    test_ladder_exhausted_without_degradation_raises,
    test_hang_watchdog_timeout_then_retry, and the sparse
    unstructured-endgame degradation test."""
    plan = [
        InjectedFault(
            FaultKind.CRASH, iteration=1, backend="tpu", times=None
        )
    ]
    r = supervised_solve(
        _problem(),
        backend="tpu",
        supervisor=_sup(fault_plan=plan, max_retries=8),
    )
    assert r.status == Status.OPTIMAL
    # first chain entry after "tpu" (the matrix-free inexact-IPM rung)
    assert r.backend == "sparse-iterative"
    assert [f.kind for f in r.faults] == [FaultKind.CRASH] * 4
    assert r.faults[-1].action == "degrade:sparse-iterative"
    np.testing.assert_allclose(
        r.objective, reference_result.objective, rtol=1e-6
    )


def test_retries_exhausted_raises_structured_failure():
    plan = [InjectedFault(FaultKind.CRASH, iteration=1, times=None)]
    with pytest.raises(SolveFailure) as ei:
        supervised_solve(
            _problem(),
            backend="cpu",
            supervisor=_sup(fault_plan=plan, max_retries=3),
        )
    e = ei.value
    assert e.status == Status.FAILED
    # max_retries recoveries were attempted; the (max_retries+1)-th fault
    # is terminal — the history holds all of them, in order.
    assert len(e.faults) == 4
    assert all(f.kind == FaultKind.CRASH for f in e.faults)
    assert e.faults[-1].action == "give_up"
    assert "InjectedCrash" in e.faults[0].detail
    assert "fault history" in str(e)


def test_ladder_exhausted_without_degradation_raises():
    plan = [InjectedFault(FaultKind.CRASH, iteration=1, times=None)]
    with pytest.raises(SolveFailure) as ei:
        supervised_solve(
            _problem(),
            backend="cpu",
            supervisor=_sup(fault_plan=plan, max_retries=20, degrade=False),
        )
    # rollback, reg bump, recenter, then no rung left: 4 faults total.
    assert len(ei.value.faults) == 4
    assert ei.value.faults[-1].action == "give_up"


def test_terminal_answers_are_not_retried():
    """ITERATION_LIMIT is an answer, not a fault — no recovery attempts."""
    r = supervised_solve(
        _problem(), backend="cpu", supervisor=_sup(), max_iter=3
    )
    assert r.status == Status.ITERATION_LIMIT
    assert r.faults == []


# ------------------------------------------------------------- injection
class TestFaultInjector:
    def test_times_budget_persists_across_wraps(self):
        from distributedlpsolver_tpu.supervisor import FaultInjector

        inj = FaultInjector(
            [InjectedFault(FaultKind.CRASH, iteration=2, times=1)]
        )
        ok = lambda: ("state", "stats")
        assert inj.wrap_step(ok, 1, "cpu") is ok  # wrong iteration
        with pytest.raises(InjectedCrash):
            inj.wrap_step(ok, 2, "cpu")()  # fires
        assert inj.wrap_step(ok, 2, "cpu") is ok  # budget consumed

    def test_backend_filter(self):
        from distributedlpsolver_tpu.supervisor import FaultInjector

        inj = FaultInjector(
            [InjectedFault(FaultKind.CRASH, iteration=1, backend="tpu")]
        )
        ok = lambda: None
        assert inj.wrap_step(ok, 1, "cpu") is ok
        assert inj.wrap_step(ok, 1, "tpu") is not ok
