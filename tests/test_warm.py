"""Warm-start & amortization layer tests (ipm/warm.py,
serve/warmcache.py, utils/fingerprint.py, the warm bucket path).

Covers the layer end to end: the shared fingerprint definitions, the
bounded LRU cache (eviction, collision rejection), the safeguarded
warm-started IPM in both engines (host driver + traced bucket program),
warm/cold mixed-batch dispatch, the seeded correlated request stream,
the service-level flow (hits, labels, zero warm recompiles), and the
restored endgame KKT-refine round (CPU-pinned equivalence)."""

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.ipm.config import SolverConfig
from distributedlpsolver_tpu.ipm.state import IPMState, Status
from distributedlpsolver_tpu.ipm.warm import WarmStart
from distributedlpsolver_tpu.models.generators import (
    correlated_request_stream,
    random_dense_lp,
    BatchedLP,
)
from distributedlpsolver_tpu.serve.warmcache import WarmCache
from distributedlpsolver_tpu.utils import fingerprint as fp_mod

pytestmark = pytest.mark.warm

# The tier-1 serve probe's request shapes (scripts/probe_serve.py /
# models/generators.random_request_stream defaults) — the shapes the
# warm-vs-cold equivalence acceptance runs on.
PROBE_SHAPES = ((8, 24), (12, 32))


def _state_of(res, k, m, n):
    return IPMState(
        x=res.x[k, :n].copy(), y=res.y[k, :m].copy(), s=res.s[k, :n].copy(),
        w=res.w[k, :n].copy(), z=res.z[k, :n].copy(),
    )


def _correlated_batch(m, n, B, jitter=0.01, seed=3):
    """One same-A batch with jittered b/c (the delta-solve workload)."""
    rng = np.random.default_rng(seed)
    base = random_dense_lp(m, n, seed=seed)
    A = np.broadcast_to(base.A, (B, m, n)).copy()
    x0 = rng.uniform(0.5, 2.0, size=n)
    b = np.stack([
        base.A @ (x0 * (1 + jitter * rng.standard_normal(n)))
        for _ in range(B)
    ])
    c = np.stack([
        base.c * (1 + jitter * rng.standard_normal(n)) for _ in range(B)
    ])
    return BatchedLP(c=c, A=A, b=b, name=f"corr_{m}x{n}")


# -- fingerprints (satellite: one definition, one test) -----------------


def test_problem_fingerprint_single_definition():
    """checkpoint.py re-exports THE fingerprint from utils/fingerprint —
    the checkpoint format and the warm cache can never drift apart."""
    from distributedlpsolver_tpu.utils import checkpoint as ckpt

    assert ckpt.problem_fingerprint is fp_mod.problem_fingerprint

    class _Inf:
        m, n = 3, 4
        c = np.arange(4.0)
        b = np.arange(3.0)

    fp1 = fp_mod.problem_fingerprint(_Inf)
    assert fp1 == fp_mod.problem_fingerprint(_Inf) and len(fp1) == 16


def test_structural_fingerprint_invariances():
    rng = np.random.default_rng(0)
    A = rng.standard_normal((6, 10))
    lb, ub = np.zeros(10), np.full(10, np.inf)
    f0 = fp_mod.structural_fingerprint(A, 6, 10, lb, ub)
    # same A, new b/c is the SAME model (b/c are not hashed at all)
    assert f0 == fp_mod.structural_fingerprint(A.copy(), 6, 10, lb, ub)
    # a changed coefficient is a different model
    A2 = A.copy()
    A2[0, 0] += 1e-9
    assert f0 != fp_mod.structural_fingerprint(A2, 6, 10, lb, ub)
    # the bounds PATTERN matters, bound values do not
    ub2 = ub.copy()
    ub2[3] = 5.0  # inf -> finite flips the pattern
    assert f0 != fp_mod.structural_fingerprint(A, 6, 10, lb, ub2)
    ub3 = ub2.copy()
    ub3[3] = 9.0  # finite -> finite keeps it
    assert fp_mod.structural_fingerprint(
        A, 6, 10, lb, ub2
    ) == fp_mod.structural_fingerprint(A, 6, 10, lb, ub3)
    # sparse hashing is deterministic and pattern-sensitive
    import scipy.sparse as sp

    S = sp.random(8, 12, density=0.3, random_state=1, format="csr")
    fs = fp_mod.structural_fingerprint(S)
    assert fs == fp_mod.structural_fingerprint(S.copy())
    S2 = S.copy()
    S2.data[0] += 1.0
    assert fs != fp_mod.structural_fingerprint(S2)


# -- warm cache ---------------------------------------------------------


def test_warmcache_lru_eviction():
    cache = WarmCache(capacity=2)
    st = IPMState(*(np.ones(2) for _ in range(5)))
    cache.store("a", m=2, n=2, state=st)
    cache.store("b", m=2, n=2, state=st)
    assert cache.lookup("a", 2, 2) is not None  # refreshes a's position
    cache.store("c", m=2, n=2, state=st)  # evicts b (LRU)
    assert cache.lookup("b", 2, 2) is None
    assert cache.lookup("a", 2, 2) is not None
    assert cache.lookup("c", 2, 2) is not None
    s = cache.stats()
    assert s["entries"] == 2 and s["evictions"] == 1


def test_warmcache_collision_rejection():
    """An entry whose recorded shapes disagree with the request is a
    collision: returned as a miss and counted, never handed out — a
    shape-coincident wrong iterate would converge to the wrong answer."""
    cache = WarmCache(capacity=4)
    st = IPMState(*(np.ones(3) for _ in range(5)))
    cache.store("k", m=3, n=3, state=st)
    assert cache.lookup("k", 5, 7) is None  # forged collision
    assert cache.stats()["collisions"] == 1
    # a colliding store never merges the old entry's fields
    cache.store("k", m=5, n=7, tol=1e-6)
    e = cache.lookup("k", 5, 7)
    assert e is not None and e.state is None


def test_warmcache_capacity_validation():
    with pytest.raises(ValueError):
        WarmCache(capacity=0)


# -- correlated stream (satellite: seeded reproducibility) --------------


def test_correlated_stream_reproducible():
    a = list(correlated_request_stream(12, seed=9))
    b = list(correlated_request_stream(12, seed=9))
    for p, q in zip(a, b):
        assert p.name == q.name
        np.testing.assert_array_equal(p.A, q.A)
        np.testing.assert_array_equal(p.b if p.rlb is None else p.rlb, q.rlb)
        np.testing.assert_array_equal(p.c, q.c)
    # offset continues the SAME stream: requests [4:12] of a 12-stream
    tail = list(correlated_request_stream(8, seed=9, offset=4))
    for p, q in zip(a[4:], tail):
        assert p.name == q.name
        np.testing.assert_array_equal(p.c, q.c)
        np.testing.assert_array_equal(p.rlb, q.rlb)
    # a different seed is a different stream (models included)
    c = list(correlated_request_stream(12, seed=10))
    assert any(
        p.A.shape != q.A.shape or not np.array_equal(p.A, q.A)
        for p, q in zip(a, c)
    )


def test_correlated_stream_same_model_shares_fingerprint():
    reqs = list(correlated_request_stream(16, n_models=2, seed=4))
    fps = {}
    for p in reqs:
        key = fp_mod.structural_fingerprint(p.A, p.m, p.n, p.lb, p.ub)
        fps.setdefault(key, 0)
        fps[key] += 1
    assert len(fps) == 2  # one key per model, b/c jitter notwithstanding
    assert all(v >= 2 for v in fps.values())


# -- bucket engine: warm-vs-cold equivalence & safeguards ---------------


def test_bucket_warm_vs_cold_equivalence_probe_shapes():
    """Across the 200-request probe shapes: warm solves reach the SAME
    1e-8 verdicts and objectives as cold, in fewer median iterations,
    with zero extra compiles (the warm lanes never fork the program)."""
    from distributedlpsolver_tpu.backends.batched import (
        bucket_cache_size,
        solve_bucket,
    )

    for m, n in PROBE_SHAPES:
        B = 8
        batch = _correlated_batch(m, n, B, jitter=0.01, seed=5)
        active = np.ones(B, dtype=bool)
        cold = solve_bucket(batch, active)
        assert all(s is Status.OPTIMAL for s in cold.status)
        warm_state = IPMState(
            x=np.broadcast_to(cold.x[0], (B, n)).copy(),
            y=np.broadcast_to(cold.y[0], (B, m)).copy(),
            s=np.broadcast_to(cold.s[0], (B, n)).copy(),
            w=np.broadcast_to(cold.w[0], (B, n)).copy(),
            z=np.broadcast_to(cold.z[0], (B, n)).copy(),
        )
        c0 = bucket_cache_size()
        warm = solve_bucket(
            batch, active, warm=warm_state, warm_mask=np.ones(B, dtype=bool)
        )
        assert bucket_cache_size() - c0 == 0, "warm dispatch recompiled"
        assert all(s is Status.OPTIMAL for s in warm.status)
        assert warm.warm_used.all()
        np.testing.assert_allclose(
            warm.objective, cold.objective,
            rtol=2e-8, atol=2e-8 * (1 + np.abs(cold.objective).max()),
        )
        assert np.median(warm.iterations) < np.median(cold.iterations)


def test_bucket_mixed_warm_cold_batch():
    """One dispatch freely mixes warm and cold members: the mask decides
    per slot, and every member still finishes OPTIMAL at 1e-8."""
    from distributedlpsolver_tpu.backends.batched import solve_bucket

    m, n, B = 12, 32, 8
    batch = _correlated_batch(m, n, B, seed=6)
    active = np.ones(B, dtype=bool)
    cold = solve_bucket(batch, active)
    warm_state = IPMState(
        x=np.broadcast_to(cold.x[0], (B, n)).copy(),
        y=np.broadcast_to(cold.y[0], (B, m)).copy(),
        s=np.broadcast_to(cold.s[0], (B, n)).copy(),
        w=np.broadcast_to(cold.w[0], (B, n)).copy(),
        z=np.broadcast_to(cold.z[0], (B, n)).copy(),
    )
    mask = np.zeros(B, dtype=bool)
    mask[::2] = True
    mixed = solve_bucket(batch, active, warm=warm_state, warm_mask=mask)
    assert all(s is Status.OPTIMAL for s in mixed.status)
    assert mixed.warm_used[::2].all()
    assert not mixed.warm_used[1::2].any()  # unmasked slots stayed cold
    np.testing.assert_allclose(
        mixed.objective, cold.objective,
        rtol=2e-8, atol=2e-8 * (1 + np.abs(cold.objective).max()),
    )
    # cold slots run the exact cold trajectory (same start, same steps)
    np.testing.assert_array_equal(
        mixed.iterations[1::2], cold.iterations[1::2]
    )


def test_bucket_segmented_warm_path():
    """The host-segmented bucket drive (the TPU-default route, forced
    here via segment_iters) runs the same safeguarded warm selection:
    equivalence, warm_used, and zero recompiles — CPU-pinned."""
    from distributedlpsolver_tpu.backends.batched import (
        bucket_cache_size,
        solve_bucket,
    )

    m, n, B = 8, 24, 4
    batch = _correlated_batch(m, n, B, seed=8)
    active = np.ones(B, dtype=bool)
    cfg = SolverConfig(segment_iters=4)
    cold = solve_bucket(batch, active, cfg)
    assert all(s is Status.OPTIMAL for s in cold.status)
    warm_state = IPMState(
        x=np.broadcast_to(cold.x[0], (B, n)).copy(),
        y=np.broadcast_to(cold.y[0], (B, m)).copy(),
        s=np.broadcast_to(cold.s[0], (B, n)).copy(),
        w=np.broadcast_to(cold.w[0], (B, n)).copy(),
        z=np.broadcast_to(cold.z[0], (B, n)).copy(),
    )
    c0 = bucket_cache_size()
    warm = solve_bucket(
        batch, active, cfg, warm=warm_state,
        warm_mask=np.ones(B, dtype=bool),
    )
    assert bucket_cache_size() - c0 == 0
    assert warm.warm_used.all()
    assert all(s is Status.OPTIMAL for s in warm.status)
    np.testing.assert_allclose(
        warm.objective, cold.objective,
        rtol=2e-8, atol=2e-8 * (1 + np.abs(cold.objective).max()),
    )
    assert warm.iterations.mean() <= cold.iterations.mean()


def test_bucket_adversarial_warm_rejected():
    """A far-off prior must fall back to the cold start per slot (the
    safeguard), and the dispatch still finishes OPTIMAL."""
    from distributedlpsolver_tpu.backends.batched import solve_bucket

    m, n, B = 8, 24, 4
    batch = _correlated_batch(m, n, B, seed=7)
    bad = IPMState(
        x=np.full((B, n), 1e9), y=np.full((B, m), -1e9),
        s=np.full((B, n), 1e9), w=np.ones((B, n)), z=np.zeros((B, n)),
    )
    r = solve_bucket(
        batch, np.ones(B, dtype=bool), warm=bad,
        warm_mask=np.ones(B, dtype=bool),
    )
    assert not r.warm_used.any()
    assert all(s is Status.OPTIMAL for s in r.status)


# -- driver engine: WarmStart seam, safeguard, warm cache ---------------


def test_driver_warm_start_cuts_iterations():
    reqs = list(correlated_request_stream(2, n_models=1, seed=11))
    r0 = solve(reqs[0], backend="cpu")
    cache = WarmCache(4)
    # seed the cache through the driver itself
    r0b = solve(reqs[0], backend="cpu", warm_cache=cache)
    assert r0b.warm == "cold"
    r1 = solve(reqs[1], backend="cpu", warm_cache=cache)
    assert r1.warm == "warm"
    assert r1.status is Status.OPTIMAL
    assert r1.iterations < r0.iterations
    s = cache.stats()
    assert s["hits"] == 1 and s["stores"] == 2


def test_driver_adversarial_warm_start_rejected():
    from distributedlpsolver_tpu.obs import metrics as obs_metrics

    p = next(correlated_request_stream(1, n_models=1, seed=12))
    bad = IPMState(
        x=np.full(p.n, 1e9), y=np.full(p.m, -1e9), s=np.full(p.n, 1e9),
        w=np.ones(p.n), z=np.zeros(p.n),
    )
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.set_registry(reg)
    try:
        r = solve(p, backend="cpu", warm_start=WarmStart(bad))
    finally:
        obs_metrics.set_registry(None)
    assert r.warm == "rejected"
    assert r.status is Status.OPTIMAL
    assert reg.counter("warm_start_rejected_total").value == 1


def test_driver_warm_start_solution_equivalence():
    reqs = list(correlated_request_stream(2, n_models=1, seed=13))
    cold = solve(reqs[1], backend="cpu")
    prior = solve(reqs[0], backend="cpu", warm_cache=(cache := WarmCache(2)))
    fp = fp_mod.structural_fingerprint(
        reqs[1].A, reqs[1].m, reqs[1].n, reqs[1].lb, reqs[1].ub
    )
    entry = cache.lookup(fp, reqs[1].m, reqs[1].n)
    assert entry is not None and prior.status is Status.OPTIMAL
    warm = solve(reqs[1], backend="cpu", warm_start=WarmStart(entry.state))
    assert warm.warm == "warm" and warm.status is Status.OPTIMAL
    assert abs(warm.objective - cold.objective) <= 1e-7 * (
        1 + abs(cold.objective)
    )


def test_supervised_solve_threads_warm_through():
    from distributedlpsolver_tpu.supervisor import supervised_solve

    cache = WarmCache(4)
    reqs = list(correlated_request_stream(3, n_models=1, seed=14))
    r0 = supervised_solve(reqs[0], backend="cpu", warm_cache=cache)
    r1 = supervised_solve(reqs[1], backend="cpu", warm_cache=cache)
    assert r0.warm == "cold" and r1.warm == "warm"
    assert r1.status is Status.OPTIMAL
    assert r1.iterations < r0.iterations


def test_driver_warm_cache_reuses_scaling_and_iterate():
    """Delta-solve amortization: the second same-structure solve reuses
    the cached Ruiz factors (the entry holds them) and the prior
    iterate, and still lands on the cold answer at 1e-8."""
    cache = WarmCache(4)
    reqs = list(correlated_request_stream(2, n_models=1, seed=15))
    cold1 = solve(reqs[1], backend="cpu")
    solve(reqs[0], backend="cpu", warm_cache=cache)
    fp = fp_mod.structural_fingerprint(
        reqs[0].A, reqs[0].m, reqs[0].n, reqs[0].lb, reqs[0].ub
    )
    entry = cache.lookup(fp, reqs[0].m, reqs[0].n)
    assert entry is not None
    assert entry.scaling is not None and entry.scaled_A is not None
    warm1 = solve(reqs[1], backend="cpu", warm_cache=cache)
    assert warm1.warm == "warm"
    assert abs(warm1.objective - cold1.objective) <= 1e-7 * (
        1 + abs(cold1.objective)
    )


# -- service flow -------------------------------------------------------


@pytest.mark.serve
def test_service_correlated_stream_warm_flow():
    """End to end through SolveService: the cold leg populates the
    fingerprint cache, the steady-state leg hits it, warm members cut
    the median iterations strictly below cold, the JSONL records carry
    the warm label, and the warm leg compiles nothing."""
    from distributedlpsolver_tpu.backends.batched import bucket_cache_size
    from distributedlpsolver_tpu.obs import metrics as obs_metrics
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    reg = obs_metrics.MetricsRegistry()
    with SolveService(
        ServiceConfig(batch=8, flush_s=0.02), metrics=reg
    ) as svc:
        futs = [
            svc.submit(p) for p in correlated_request_stream(24, seed=21)
        ]
        assert svc.drain(timeout=600)
        cold_rs = [f.result(timeout=60) for f in futs]
        c0 = bucket_cache_size()
        futs = [
            svc.submit(p)
            for p in correlated_request_stream(32, seed=21, offset=24)
        ]
        assert svc.drain(timeout=600)
        warm_rs = [f.result(timeout=60) for f in futs]
        recompiles = bucket_cache_size() - c0
        stats = svc.stats()

    assert recompiles == 0
    all_rs = cold_rs + warm_rs
    assert all(r.status is Status.OPTIMAL for r in all_rs)
    hits = [r for r in warm_rs if r.warm == "warm"]
    assert hits, "steady-state leg produced no warm-cache hits"
    colds = [r for r in all_rs if r.warm != "warm"]
    med_warm = np.median([r.iterations for r in hits])
    med_cold = np.median([r.iterations for r in colds])
    assert med_warm < med_cold
    # acceptance bar: >= 30% median iteration reduction on the stream
    assert med_warm <= 0.7 * med_cold
    # telemetry: the record schema carries the label, stats the cache.
    # NOTE: cold-leg requests may warm too (same-model batches earlier
    # in the leg populate the cache), so totals count across BOTH legs.
    assert all(r.record()["warm"] in ("warm", "cold", "rejected")
               for r in all_rs)
    all_warm = [r for r in all_rs if r.warm == "warm"]
    wc = stats["warm_cache"]
    assert wc["hits"] >= len(all_warm) and wc["entries"] >= 1
    assert stats["warm"]["requests"] == len(all_warm)
    # metrics: hit/miss counters and the warm/cold iteration histograms
    assert reg.counter("warm_cache_hits_total").value >= len(all_warm)
    assert reg.counter("warm_cache_misses_total").value >= 1
    h_warm = reg.histogram(
        "ipm_iterations", buckets=obs_metrics.ITER_BUCKETS,
        labels={"start": "warm"},
    )
    h_cold = reg.histogram(
        "ipm_iterations", buckets=obs_metrics.ITER_BUCKETS,
        labels={"start": "cold"},
    )
    # The demux observes every bucket member; labels on final results
    # match exactly when nothing fell back to the solo path.
    if not any(r.retried_solo for r in all_rs):
        assert h_warm.count == len(all_warm)
        assert h_cold.count == len(all_rs) - len(all_warm)
    else:  # solo retries re-solve outside this registry's histograms
        assert h_warm.count >= len(all_warm) - sum(
            1 for r in all_rs if r.retried_solo
        )


@pytest.mark.serve
def test_service_warm_disabled():
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    with SolveService(
        ServiceConfig(batch=4, flush_s=0.01, warm_start=False)
    ) as svc:
        futs = [
            svc.submit(p) for p in correlated_request_stream(8, seed=22)
        ]
        assert svc.drain(timeout=600)
        rs = [f.result(timeout=60) for f in futs]
        stats = svc.stats()
    assert all(r.status is Status.OPTIMAL for r in rs)
    assert all(r.warm == "cold" for r in rs)
    assert stats["warm_cache"] is None


# -- endgame KKT refine (satellite: ROUND5_NOTES lever 1) ---------------


def test_endgame_step_params_policy():
    from distributedlpsolver_tpu.backends.dense import _endgame_step_params

    assert _endgame_step_params(SolverConfig()).kkt_refine == 1  # auto
    assert _endgame_step_params(
        SolverConfig(endgame_kkt_refine=0)
    ).kkt_refine == 0  # legacy escape hatch
    assert _endgame_step_params(
        SolverConfig(endgame_kkt_refine=3)
    ).kkt_refine == 3
    # host mode caps at 1 regardless of either knob
    assert _endgame_step_params(
        SolverConfig(endgame_kkt_refine=3), host_mode=True
    ).kkt_refine == 1
    assert _endgame_step_params(
        SolverConfig(kkt_refine=0), host_mode=True
    ).kkt_refine == 0
    # mcc rides along unchanged
    assert _endgame_step_params(SolverConfig(endgame_mcc=4)).mcc == 4


def test_endgame_refine_round_equivalence_cpu():
    """CPU-pinned equivalence of the restored KKT-refine round: a mini
    endgame loop (assemble → factor → split-dispatch step, exactly the
    _endgame_loop sequence) run with 0 and 1 refinement rounds reaches
    the same 1e-8 optimum; the refined run never needs MORE iterations.
    The TPU iteration-count measurement is deferred to the next
    accelerator round (ISSUE 8 satellite)."""
    import jax.numpy as jnp

    from distributedlpsolver_tpu.backends.dense import (
        _endgame_assemble,
        _endgame_factor,
        _endgame_step,
        _endgame_step_params,
    )
    from distributedlpsolver_tpu.ipm import core
    from distributedlpsolver_tpu.models.problem import to_interior_form

    p = random_dense_lp(8, 20, seed=17)
    inf = to_interior_form(p)
    data = core.make_problem_data(
        jnp, inf.c, inf.b, np.full(inf.n, np.inf), jnp.float64
    )
    A = jnp.asarray(inf.A, dtype=jnp.float64)

    def run(n_refine):
        cfg = SolverConfig(endgame_kkt_refine=n_refine, endgame_mcc=0)
        params = _endgame_step_params(cfg)
        assert params.kkt_refine == n_refine
        ops = core.LinOps(
            xp=jnp,
            matvec=lambda v: A @ v,
            rmatvec=lambda v: A.T @ v,
            factorize=lambda d: jnp.linalg.cholesky(
                (A * d) @ A.T
                + 1e-10 * jnp.eye(inf.m, dtype=jnp.float64)
            ),
            solve=lambda L, r: jax.scipy.linalg.cho_solve((L, True), r),
        )
        state = core.starting_point(ops, data, params)
        reg = 1e-10
        for it in range(60):
            M = _endgame_assemble(A, data, state, params)
            L = _endgame_factor(M, jnp.asarray(reg, jnp.float64))
            diagM = jnp.diagonal(M)
            state, stats = _endgame_step(
                A, data, state, L, jnp.asarray(reg, jnp.float64),
                diagM, params,
            )
            assert not bool(stats.bad)
            if (
                float(stats.rel_gap) <= 1e-8
                and float(stats.pinf) <= 1e-8
                and float(stats.dinf) <= 1e-8
            ):
                return it + 1, float(stats.pobj)
        raise AssertionError(f"no convergence with refine={n_refine}")

    import jax

    it0, obj0 = run(0)
    it1, obj1 = run(1)
    assert abs(obj1 - obj0) <= 1e-7 * (1 + abs(obj0))
    assert it1 <= it0  # the refine round never costs iterations
