"""Structural presolve: reductions, postsolve exactness, dual recovery.

Oracle strategy (SURVEY.md §4): HiGHS on the *original* problem must agree
with presolve+IPM on the reduced one; dual recovery is validated through
strong duality computed entirely in the original space.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from distributedlpsolver_tpu.ipm import solve
from distributedlpsolver_tpu.ipm.state import Status
from distributedlpsolver_tpu.models.generators import random_general_lp
from distributedlpsolver_tpu.models.presolve import presolve
from distributedlpsolver_tpu.models.problem import LPProblem

from tests.oracle import highs_on_general

INF = np.inf


def _dual_objective(p: LPProblem, y: np.ndarray, s: np.ndarray) -> float:
    """General-form dual objective at (y, s): Σ_i y_i·(rlb if y_i>0 else rub)
    + Σ_j (s_j⁺·lb_j + s_j⁻·ub_j) + c0. Finite iff every positive
    multiplier pairs with a finite bound — which exact recovery guarantees."""
    # Multipliers below the solve tolerance are numerically zero; without
    # clipping, a 1e-11 residual multiplier pairing an infinite bound would
    # poison the sum with ±inf.
    y = np.where(np.abs(y) > 1e-7 * (1 + np.abs(y).max()), y, 0.0)
    s = np.where(np.abs(s) > 1e-7 * (1 + np.abs(s).max()), s, 0.0)
    row_terms = np.where(y > 0, p.rlb, np.where(y < 0, p.rub, 0.0))
    col_terms = np.where(s > 0, p.lb, np.where(s < 0, p.ub, 0.0))
    return float(y @ np.where(y != 0, row_terms, 0.0)
                 + s @ np.where(s != 0, col_terms, 0.0)) + p.c0


def _check_solution(p: LPProblem, r, oracle_obj: float, tol: float = 1e-6):
    assert r.status == Status.OPTIMAL
    assert r.objective == pytest.approx(oracle_obj, abs=tol * (1 + abs(oracle_obj)))
    assert p.max_violation(r.x) < 1e-6
    # dual recovery: c - Aᵀy = s exactly, strong duality to oracle obj
    resid = p.c - np.asarray(p.A.T @ r.y).ravel() - r.s
    assert np.max(np.abs(resid)) < 1e-8 * (1 + np.max(np.abs(p.c)))
    dobj = _dual_objective(p, r.y, r.s)
    assert np.isfinite(dobj)
    sense = -1.0 if p.maximize else 1.0
    assert sense * r.objective == pytest.approx(dobj, abs=1e-5 * (1 + abs(dobj)))


def _mini_lp(**kw):
    """3 vars, rows: equality + redundant + singleton; col 2 fixed."""
    defaults = dict(
        c=[1.0, 2.0, 3.0],
        A=[
            [1.0, 1.0, 1.0],   # equality x0+x1+x2 = 10
            [1.0, 0.0, 0.0],   # singleton: 2 <= x0 <= 8
            [1.0, 1.0, 1.0],   # redundant copy with slack range
        ],
        rlb=[10.0, 2.0, -100.0],
        rub=[10.0, 8.0, 100.0],
        lb=[0.0, 0.0, 4.0],
        ub=[INF, 20.0, 4.0],  # x2 fixed at 4; x1's finite ub keeps row 2's
        # activity range finite so the redundancy scan can retire it
        name="mini",
    )
    defaults.update(kw)
    return LPProblem(**defaults)


class TestReductions:
    def test_mini_counts(self):
        red, info = presolve(_mini_lp())
        assert info.status is None
        assert info.reductions["singleton_rows"] == 1
        assert info.reductions["fixed_cols"] == 1
        assert info.reductions["redundant_rows"] >= 1
        m_red, n_red = info.reduced_shape
        assert n_red == 2 and m_red == 1
        assert red.shape == (m_red, n_red)

    def test_sparse_matches_dense(self):
        p = _mini_lp()
        ps = _mini_lp(A=sp.csr_matrix(np.asarray(p.A)))
        rd, infd = presolve(p)
        rs, infs = presolve(ps)
        assert infd.reduced_shape == infs.reduced_shape
        assert np.allclose(rd.rlb, rs.rlb) and np.allclose(rd.lb, rs.lb)

    def test_fixpoint_cascade(self):
        # singleton row fixes x0 → x0 substitution makes row 1 a singleton
        # on x1 → fixes x1 → row 2 becomes empty (feasible) → drop.
        p = LPProblem(
            c=[1.0, 1.0],
            A=[[1.0, 0.0], [1.0, 1.0], [0.0, 0.0]],
            rlb=[3.0, 5.0, -1.0],
            rub=[3.0, 5.0, 1.0],
            lb=[0.0, 0.0],
            ub=[INF, INF],
        )
        red, info = presolve(p)
        assert info.status == Status.OPTIMAL
        x = info.postsolve_x(np.empty(0))
        assert x == pytest.approx([3.0, 2.0])
        assert info.objective == pytest.approx(5.0)

    def test_empty_column_cost_direction(self):
        p = LPProblem(
            c=[1.0, -2.0, 0.0],
            A=[[0.0, 0.0, 0.0]],
            rlb=[-1.0],
            rub=[1.0],
            lb=[1.0, 0.0, -3.0],
            ub=[5.0, 7.0, 8.0],
        )
        red, info = presolve(p)
        assert info.status == Status.OPTIMAL
        x = info.postsolve_x(np.empty(0))
        # c>0 → lb; c<0 → ub; c=0 → any feasible (clamp of 0)
        assert x[0] == pytest.approx(1.0)
        assert x[1] == pytest.approx(7.0)
        assert p.lb[2] <= x[2] <= p.ub[2]


class TestEarlyStatus:
    def test_infeasible_crossing_bounds(self):
        # x ≤ -1 (singleton row) conflicts with lb = 0
        p = LPProblem(
            c=[1.0], A=[[1.0]], rlb=[-INF], rub=[-1.0], lb=[0.0], ub=[INF]
        )
        _, info = presolve(p)
        assert info.status == Status.PRIMAL_INFEASIBLE

    def test_infeasible_row_activity(self):
        # x0 + x1 >= 10 with x0,x1 <= 2 is unsatisfiable
        p = LPProblem(
            c=[1.0, 1.0],
            A=[[1.0, 1.0]],
            rlb=[10.0],
            rub=[INF],
            lb=[0.0, 0.0],
            ub=[2.0, 2.0],
        )
        _, info = presolve(p)
        assert info.status == Status.PRIMAL_INFEASIBLE

    def test_unbounded_free_costless_constraintless(self):
        # empty column with negative cost and no upper bound
        p = LPProblem(
            c=[-1.0], A=sp.csr_matrix((0, 1)), rlb=np.empty(0), rub=np.empty(0),
            lb=[0.0], ub=[INF],
        )
        _, info = presolve(p)
        assert info.status == Status.DUAL_INFEASIBLE

    def test_driver_returns_presolve_status(self):
        p = LPProblem(
            c=[1.0], A=[[1.0]], rlb=[-INF], rub=[-1.0], lb=[0.0], ub=[INF]
        )
        r = solve(p, backend="cpu")
        assert r.status == Status.PRIMAL_INFEASIBLE
        assert r.iterations == 0


class TestEndToEnd:
    def test_mini_solve_matches_highs(self):
        p = _mini_lp()
        ref = highs_on_general(p)
        r = solve(p, backend="cpu")
        _check_solution(p, r, ref.fun)

    def test_presolve_off_same_objective(self):
        p = _mini_lp()
        r_on = solve(p, backend="cpu")
        r_off = solve(p, backend="cpu", presolve=False)
        assert r_on.objective == pytest.approx(r_off.objective, abs=1e-6)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_with_structure_matches_highs(self, seed):
        rng = np.random.default_rng(seed)
        p = random_general_lp(18, 30, seed=seed)
        # Inject presolve-visible structure: zero out a batch of entries,
        # fix some cols, add singleton + empty + redundant rows.
        A = np.asarray(p.A).copy()
        A[rng.random(A.shape) < 0.3] = 0.0
        m, n = A.shape
        extra = np.zeros((3, n))
        extra[0, 4] = 2.0  # singleton row: 1 ≤ 2·x4 ≤ 6
        A2 = np.vstack([A, extra])
        rlb = np.concatenate([p.rlb, [1.0, -1.0, -INF]])
        rub = np.concatenate([p.rub, [6.0, 1.0, INF]])
        lb, ub = p.lb.copy(), p.ub.copy()
        lb[7] = ub[7] = 0.5  # fixed col
        lb = np.minimum(lb, ub)
        q = LPProblem(c=p.c, A=A2, rlb=rlb, rub=rub, lb=lb, ub=ub, name="structured")
        ref = highs_on_general(q)
        if ref.status != 0:
            pytest.skip("oracle did not find the perturbed problem optimal")
        red, info = presolve(q)
        assert info.reductions["singleton_rows"] >= 1
        r = solve(q, backend="cpu")
        _check_solution(q, r, ref.fun, tol=1e-5)

    def test_singleton_dual_attribution(self):
        # min x subject only to singleton row x >= 3: the row's bound binds
        # (orig lb=0 is looser) so its multiplier must absorb s = c.
        p = LPProblem(
            c=[1.0, 1.0],
            A=[[1.0, 0.0], [1.0, 1.0]],
            rlb=[3.0, -INF],
            rub=[INF, 100.0],
            lb=[0.0, 0.0],
            ub=[INF, INF],
        )
        ref = highs_on_general(p)
        r = solve(p, backend="cpu")
        _check_solution(p, r, ref.fun)
        assert r.y[0] == pytest.approx(1.0, abs=1e-6)  # absorbed reduced cost
        assert abs(r.s[0]) < 1e-6


class TestDualCascade:
    def test_cascaded_singletons_dual_feasible(self):
        # Row 0 fixes x0=3, which turns row 1 (x0+x1=5) into a singleton on
        # x1 — both rows share column x0, so a one-shot multiplier pass
        # double-counts and returns s[0]=-1 paired with ub=+inf (dual
        # objective -inf). Reverse replay must give y=[0,1,0], s=0.
        p = LPProblem(
            c=[1.0, 1.0],
            A=[[1.0, 0.0], [1.0, 1.0], [0.0, 0.0]],
            rlb=[3.0, 5.0, -1.0],
            rub=[3.0, 5.0, 1.0],
            lb=[0.0, 0.0],
            ub=[INF, INF],
        )
        r = solve(p, backend="cpu")
        assert r.status == Status.OPTIMAL and r.iterations == 0
        _check_solution(p, r, 5.0)
        assert r.y == pytest.approx([0.0, 1.0, 0.0], abs=1e-9)
        assert r.s == pytest.approx([0.0, 0.0], abs=1e-9)

    def test_unbounded_objective_sign(self):
        base = dict(
            A=sp.csr_matrix((0, 1)), rlb=np.empty(0), rub=np.empty(0),
            lb=[0.0], ub=[INF],
        )
        r_min = solve(LPProblem(c=[-1.0], **base), backend="cpu")
        assert r_min.status == Status.DUAL_INFEASIBLE
        assert r_min.objective == -INF  # min -x unbounded BELOW
        # maximize stores c minimized: max x ≡ min -x with maximize=True
        r_max = solve(LPProblem(c=[-1.0], maximize=True, **base), backend="cpu")
        assert r_max.status == Status.DUAL_INFEASIBLE
        assert r_max.objective == INF

    def test_duals_original_space_without_presolve(self):
        p = _mini_lp()
        r = solve(p, backend="cpu", presolve=False)
        assert r.y.shape == (p.m,) and r.s.shape == (p.n,)
        _check_solution(p, r, 18.0, tol=1e-5)


class TestPostsolveShapes:
    def test_x_y_s_full_dimension(self):
        p = _mini_lp()
        r = solve(p, backend="cpu")
        assert r.x.shape == (p.n,)
        assert r.y.shape == (p.m,)
        assert r.s.shape == (p.n,)
