"""Network serving plane tests (README "Network serving"): protocol
parsing, SLO-aware admission (token-bucket quotas, weighted-fair
shares, priority flush shading), EDF slot assignment, the HTTP
front-end surface (solve/metrics/healthz/statusz, sync + async), the
router tier (shape/load routing, health-checked failover), and the
probe_net.py tier-1 smoke.

All CPU; servers bind ephemeral localhost ports.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.net import (
    AdmissionConfig,
    AdmissionController,
    NetConfig,
    ProtocolError,
    SolveHTTPServer,
    TenantQuota,
    parse_solve_request,
    peek_route_hint,
)
from distributedlpsolver_tpu.net.router import (
    Router,
    RouterConfig,
    RouterHTTPServer,
)
from distributedlpsolver_tpu.obs.metrics import MetricsRegistry
from distributedlpsolver_tpu.serve import (
    BucketSpec,
    BucketTable,
    ServiceConfig,
    ServiceOverloaded,
    SolveService,
)
from distributedlpsolver_tpu.serve.scheduler import PendingRequest, Scheduler

pytestmark = pytest.mark.net

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _req(request_id, now, deadline=None, flush_scale=1.0, m=8, n=24):
    rng = np.random.default_rng(request_id)
    return PendingRequest(
        request_id=request_id,
        name=f"r{request_id}",
        c=rng.standard_normal(n),
        A=rng.standard_normal((m, n)),
        b=rng.standard_normal(m),
        tol=1e-8,
        future=None,
        t_submit=now,
        deadline=deadline,
        flush_scale=flush_scale,
    )


def _http(url, body=None, timeout=60.0):
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}


# ---------------------------------------------------------------------------
# protocol


def test_parse_json_inline_problem():
    p = random_dense_lp(4, 9, seed=3)
    body = json.dumps(
        {
            "problem": {
                "c": p.c.tolist(),
                "A": np.asarray(p.A).tolist(),
                "b": p.rlb.tolist(),
            },
            "tol": 1e-6,
            "deadline_ms": 250,
            "tenant": "acme",
            "priority": "high",
            "id": "job-1",
        }
    ).encode()
    req = parse_solve_request(body, "application/json")
    assert req.problem.m == 4 and req.problem.n == 9
    assert req.tol == 1e-6
    assert req.deadline_s == 0.25
    assert req.tenant == "acme" and req.priority == "high"
    assert req.name == "job-1" and not req.want_async


def test_parse_generated_and_query_fields():
    req = parse_solve_request(
        json.dumps({"m": 6, "n": 14, "seed": 1}).encode(),
        "application/json",
        query="tenant=t9&deadline_ms=100",
    )
    assert req.problem.m == 6 and req.tenant == "t9"
    assert req.deadline_s == 0.1


def test_parse_mps_body(tmp_path):
    from distributedlpsolver_tpu.io.mps import write_mps

    p = random_dense_lp(3, 7, seed=5)
    path = tmp_path / "p.mps"
    write_mps(p, str(path))
    req = parse_solve_request(
        path.read_bytes(), "text/plain", query="tenant=mps&tol=1e-7"
    )
    assert req.problem.m == 3 and req.problem.n == 7
    assert req.tenant == "mps" and req.tol == 1e-7


@pytest.mark.parametrize(
    "body,ctype",
    [
        (b"not json", "application/json"),
        (b"{}", "application/json"),
        (b'{"problem": {"c": [1], "A": [[1, 2]], "b": [1]}}',
         "application/json"),
        (b"", "text/plain"),
    ],
)
def test_parse_rejects_malformed(body, ctype):
    with pytest.raises(ProtocolError):
        parse_solve_request(body, ctype)


def test_result_payload_strict_json_for_timeout():
    """TIMEOUT/FAILED results carry inf gaps and a NaN objective; the
    wire body must still be strict JSON (Infinity/NaN are not) so
    clients can parse exactly the error responses."""
    from distributedlpsolver_tpu.net import result_payload
    from distributedlpsolver_tpu.serve.records import RequestResult

    r = RequestResult(
        request_id=7, name="late", status=Status.TIMEOUT,
        objective=float("nan"), x=None, iterations=0,
        rel_gap=float("inf"), pinf=float("inf"), dinf=float("inf"),
        bucket=(8, 24, 4), queue_ms=12.0, compile_ms=0.0, solve_ms=0.0,
        total_ms=12.0, padding_waste=0.0,
    )
    code, body = result_payload(r)
    assert code == 504
    text = json.dumps(body, allow_nan=False)  # raises on Infinity/NaN
    parsed = json.loads(text)
    assert parsed["status"] == "timeout"
    assert parsed["objective"] is None
    assert parsed["rel_gap"] is None and parsed["pinf"] is None


def test_peek_route_hint():
    assert peek_route_hint(
        json.dumps({"m": 8, "n": 24, "tol": 1e-6}).encode(),
        "application/json",
    ) == (8, 24, 1e-6)
    inline = json.dumps(
        {"problem": {"c": [1, 2, 3], "A": [[1, 2, 3]], "b": [4]}}
    ).encode()
    assert peek_route_hint(inline, "application/json") == (1, 3, 1e-8)
    assert peek_route_hint(b"RAW MPS", "text/plain") is None
    assert peek_route_hint(b"RAW MPS", "text/plain", query="m=5&n=9") == (
        5, 9, 1e-8,
    )


# ---------------------------------------------------------------------------
# admission: quotas, fairness, priority shading


def test_quota_exhaustion_and_refill():
    clock = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(quotas={"t": TenantQuota(rate=10.0, burst=2.0)}),
        max_depth=100,
        clock=lambda: clock[0],
    )
    assert ctl.admit("t").admitted
    assert ctl.admit("t").admitted
    v = ctl.admit("t")
    assert not v.admitted and v.reason == "quota"
    assert 0 < v.retry_after_s <= 0.1  # next token at rate 10/s
    clock[0] += v.retry_after_s  # wait exactly the hint -> admitted
    assert ctl.admit("t").admitted
    stats = ctl.stats()["t"]
    assert stats["admitted"] == 3 and stats["rejected"] == {"quota": 1}


def test_zero_rate_quota_hint_is_finite():
    """rate=0 with finite burst: once the bucket drains, the retry hint
    must clamp to max_retry_after_s — an inf hint breaks the Retry-After
    header, strict-JSON bodies, and client sleep(wait) loops."""
    cfg = AdmissionConfig(
        quotas={"frozen": TenantQuota(rate=0.0, burst=1.0)},
        max_retry_after_s=5.0,
    )
    ctl = AdmissionController(cfg, max_depth=100)
    assert ctl.admit("frozen").admitted
    v = ctl.admit("frozen")
    assert not v.admitted and v.reason == "quota"
    assert v.retry_after_s == 5.0  # finite, exactly the clamp


def test_tenant_state_and_metric_labels_bounded():
    """Client-controlled tenant strings must not grow server state or
    metric cardinality without bound: idle unconfigured tenant states
    LRU-evict past max_tracked_tenants, and novel tenants past
    max_tenant_labels share the 'other' metric label."""
    cfg = AdmissionConfig(
        quotas={"vip": TenantQuota(weight=2.0)},
        max_tracked_tenants=16,
        max_tenant_labels=4,
    )
    ctl = AdmissionController(cfg, max_depth=100)
    ctl.admit("vip")
    for k in range(200):
        ctl.admit(f"rando-{k}")
    stats = ctl.stats()
    assert "vip" in stats  # configured tenants are never evicted
    assert len(stats) <= 16 + 1  # unconfigured cap + the configured one
    # Labels: the first 4 strangers keep their own label; everything
    # after collapses into "other"; configured tenants always keep
    # theirs.
    labels = {ctl.labeler.label(f"rando-{k}") for k in range(200)}
    assert labels == {"rando-0", "rando-1", "rando-2", "rando-3", "other"}
    assert ctl.labeler.label("vip") == "vip"


def test_unmetered_tenant_never_quota_rejected():
    ctl = AdmissionController(AdmissionConfig(), max_depth=100)
    for _ in range(500):
        assert ctl.admit("anyone").admitted


def test_weighted_fair_rejects_hog_under_contention_only():
    ctl = AdmissionController(
        AdmissionConfig(
            quotas={
                "hog": TenantQuota(weight=1.0),
                "vip": TenantQuota(weight=3.0),
            },
            fair_start=0.5,
        ),
        max_depth=16,
    )
    # Below the contention threshold the hog may burst past its share.
    for _ in range(7):
        assert ctl.admit("hog").admitted
        ctl.on_admitted("hog")
    # Past fair_start (8 of 16): hog's share is 1/4 of 16 = 4 < 7 held.
    ctl.on_admitted("hog")  # 8 in system
    v = ctl.admit("hog")
    assert not v.admitted and v.reason == "fair"
    assert v.retry_after_s > 0
    # The vip's share (3/4 of 16 = 12) still has room.
    assert ctl.admit("vip").admitted
    # Hog work finishing frees its share again.
    for _ in range(6):
        ctl.on_finished("hog")
    assert ctl.admit("hog").admitted


def test_priority_flush_scale_defaults():
    ctl = AdmissionController(AdmissionConfig(), max_depth=8)
    assert ctl.flush_scale("high") == 0.25
    assert ctl.flush_scale("normal") == 1.0
    assert ctl.flush_scale("batch") == 4.0
    assert ctl.flush_scale("unknown-class") == 1.0


def test_service_overloaded_carries_verdict():
    svc = SolveService(
        ServiceConfig(
            batch=4, flush_s=0.02, max_queue_depth=100,
            admission=AdmissionConfig(
                quotas={"q": TenantQuota(rate=1.0, burst=1.0)}
            ),
        ),
        auto_start=False,
    )
    try:
        svc.submit(random_dense_lp(4, 9, seed=0), tenant="q")
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(random_dense_lp(4, 9, seed=1), tenant="q")
        assert ei.value.reason == "quota"
        assert ei.value.tenant == "q"
        assert ei.value.retry_after_s > 0
        assert svc.stats()["admission"]["q"]["rejected"] == {"quota": 1}
    finally:
        svc.shutdown(drain=False)


# ---------------------------------------------------------------------------
# scheduler: EDF slot assignment + priority-shaded flush


def test_edf_pop_orders_by_deadline_then_arrival():
    table = BucketTable(None, batch=4)
    s = Scheduler(table, max_depth=100, flush_s=10.0)
    now = 100.0
    # Arrival order: no-deadline, late deadline, early deadline, middle.
    reqs = [
        _req(0, now + 0.00),
        _req(1, now + 0.01, deadline=now + 9.0),
        _req(2, now + 0.02, deadline=now + 1.0),
        _req(3, now + 0.03, deadline=now + 5.0),
    ]
    for p in reqs:
        s.add(p)
    key = next(iter(s.occupancy()))  # all same shape -> one queue
    live, expired = s.pop(
        (table.spec_for(8, 24), 1e-8, "ipm"), now + 0.1
    )
    assert not expired
    # EDF: earliest deadline first; the deadline-less request sorts last.
    assert [p.request_id for p in live] == [2, 3, 1, 0]


def test_edf_pop_keeps_fifo_without_deadlines_and_splits_expired():
    table = BucketTable(None, batch=2)
    s = Scheduler(table, max_depth=100, flush_s=10.0)
    now = 10.0
    for i in range(4):
        s.add(_req(i, now + i * 0.01))
    s.add(_req(99, now, deadline=now + 0.05))  # expires before pop
    live, expired = s.pop((table.spec_for(8, 24), 1e-8, "ipm"), now + 1.0)
    # Expired split out even though it was beyond the batch head.
    assert [p.request_id for p in expired] == [99]
    assert [p.request_id for p in live] == [0, 1]  # FIFO preserved
    live2, _ = s.pop((table.spec_for(8, 24), 1e-8, "ipm"), now + 1.0)
    assert [p.request_id for p in live2] == [2, 3]
    assert s.depth() == 0


def test_priority_flush_scale_shades_ready_and_next_event():
    table = BucketTable(None, batch=8)
    s = Scheduler(table, max_depth=100, flush_s=1.0)
    now = 50.0
    s.add(_req(0, now, flush_scale=4.0))  # batch class: flush at 4 s
    assert s.ready(now + 1.5) == []  # plain flush_s would have fired
    t = s.next_event_in(now + 1.5)
    assert t == pytest.approx(2.5, abs=1e-6)
    s.add(_req(1, now + 2.0, flush_scale=0.25))  # high: flush at .25 s
    key = (table.spec_for(8, 24), 1e-8, "ipm")
    assert s.ready(now + 2.3) == [key]


def _flood_leg(admission):
    """One starvation-scenario leg: 8 threads flood 'loose' traffic
    while 10 'tight' requests arrive on a steady clock. Returns the
    tight tenant's sorted queue waits (ms), the flood's results, and
    how often either side was shed."""
    svc = SolveService(
        ServiceConfig(
            batch=8, flush_s=0.02, max_queue_depth=64, pipeline_depth=1,
            admission=admission,
        )
    )
    loose_f, tight_f = [], []
    shed = {"loose": 0, "tight": 0}
    try:
        # Warm the (8,24) bucket program first: the measured phase is
        # about queueing policy, not the one-time compile.
        warm = [
            svc.submit(random_dense_lp(8, 24, seed=k), tenant="warm")
            for k in range(8)
        ]
        assert svc.drain(timeout=300)
        assert all(
            f.result(timeout=10).status is Status.OPTIMAL for f in warm
        )
        stop = threading.Event()
        lock = threading.Lock()

        def flood():
            # Sustained: keeps submitting for the whole tight stream
            # (bounded at 400 futures so the drain stays finite).
            k = 0
            while not stop.is_set():
                with lock:
                    if len(loose_f) >= 400:
                        return
                try:
                    fut = svc.submit(
                        random_dense_lp(8, 24, seed=500 + k),
                        tenant="loose",
                    )
                    with lock:
                        loose_f.append(fut)
                except ServiceOverloaded:
                    with lock:
                        shed["loose"] += 1
                    time.sleep(0.002)
                k += 1

        flooders = [threading.Thread(target=flood) for _ in range(8)]
        for t in flooders:
            t.start()
        time.sleep(0.1)  # let the flood build a real queue
        for k in range(10):
            t_first = time.perf_counter()
            while True:
                try:
                    fut = svc.submit(
                        random_dense_lp(8, 24, seed=900 + k),
                        tenant="tight",
                        priority="high",
                        deadline=30.0,
                    )
                    break
                except ServiceOverloaded:
                    # Without the SLO layer the depth backstop sheds the
                    # tight tenant too — that IS starvation; count it
                    # and keep trying like a real client would. The
                    # retry delay is part of the tenant's wait.
                    shed["tight"] += 1
                    time.sleep(0.005)
            tight_f.append(
                (fut, (time.perf_counter() - t_first) * 1e3)
            )
            time.sleep(0.03)
        stop.set()
        for t in flooders:
            t.join(timeout=30)
        assert svc.drain(timeout=120)
        tight_r = [(f.result(timeout=10), d) for f, d in tight_f]
        loose_r = [f.result(timeout=10) for f in loose_f]
    finally:
        svc.shutdown(drain=False)
    assert all(r.status is Status.OPTIMAL for r, _ in tight_r)
    assert all(r.status is Status.OPTIMAL for r in loose_r)
    assert all(r.tenant == "tight" for r, _ in tight_r)
    # The tenant-perspective wait: admission retry delay (the 429/shed
    # loop) + post-admission queue wait until slot assignment.
    return sorted(d + r.queue_ms for r, d in tight_r), loose_r, shed


def test_tight_slo_tenant_not_starved_by_loose_flood():
    """Starvation A/B: the same tight-SLO stream under the same loose
    flood, with the SLO-aware layer ON (weighted-fair admission + EDF +
    priority flush shading) vs OFF (plain FIFO, depth backstop only).
    The layer must shed the flood, never the tight tenant."""
    slo = AdmissionConfig(
        quotas={
            "tight": TenantQuota(weight=3.0),
            "loose": TenantQuota(weight=1.0),
        },
        fair_start=0.25,
    )
    _, _, shed_slo = _flood_leg(slo)
    _, _, shed_fifo = _flood_leg(None)
    # The flood really overloaded both legs.
    assert shed_slo["loose"] >= 1
    assert shed_fifo["loose"] >= 1
    # With the layer on, the tight tenant is never shed at admission —
    # THE invariant this test pins, timing-independent.
    assert shed_slo["tight"] == 0
    # No FIFO-starvation or cross-leg latency assertions: whether the
    # depth backstop catches the tight tenant behind the flood depends
    # on thread interleaving under CI load, and the legs run
    # sequentially so their wait distributions sample different
    # ambient-load windows. The policies also shape different
    # distributions by design — FIFO starvation is bimodal (fast
    # majority + starved tail) while weighted-fair admission spreads
    # moderate waits uniformly, so the SLO leg's median legitimately
    # sits above FIFO's with zero starvation anywhere. Starvation is
    # the claim, and the shed asymmetry above pins it.


# ---------------------------------------------------------------------------
# HTTP front-end


@pytest.fixture
def backend():
    reg = MetricsRegistry()
    svc = SolveService(
        ServiceConfig(
            batch=4, flush_s=0.02, max_queue_depth=64,
            admission=AdmissionConfig(
                quotas={"limited": TenantQuota(rate=2.0, burst=1.0)}
            ),
        ),
        metrics=reg,
    )
    front = SolveHTTPServer(
        svc, NetConfig(healthz_cache_s=0.02), metrics=reg
    ).start()
    yield front
    front.shutdown()
    svc.shutdown()


def test_http_sync_solve_and_records(backend):
    code, out = _http(
        backend.url + "/v1/solve",
        {"m": 8, "n": 24, "seed": 4, "tenant": "acme", "id": "sync-1"},
    )
    assert code == 200
    assert out["status"] == "optimal" and out["tenant"] == "acme"
    assert out["name"] == "sync-1"
    assert len(out["x"]) == 24
    # Objective agrees with a direct solve of the same generated LP.
    from distributedlpsolver_tpu.ipm import solve

    ref = solve(random_dense_lp(8, 24, seed=4))
    assert out["objective"] == pytest.approx(ref.objective, rel=1e-6)


def test_http_mps_body_roundtrip(backend, tmp_path):
    from distributedlpsolver_tpu.io.mps import write_mps

    p = random_dense_lp(6, 14, seed=8)
    path = tmp_path / "p.mps"
    write_mps(p, str(path))
    req = urllib.request.Request(
        backend.url + "/v1/solve?tenant=mps",
        data=path.read_bytes(),
        headers={"Content-Type": "text/plain"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        out = json.loads(r.read())
    assert out["status"] == "optimal" and out["tenant"] == "mps"


def test_http_bad_request_is_400(backend):
    code, out = _http(backend.url + "/v1/solve", {"nope": 1})
    assert code == 400 and "error" in out
    code, _ = _http(backend.url + "/v1/nothing", {"m": 4, "n": 9})
    assert code == 404


def test_http_async_flow(backend):
    code, out = _http(
        backend.url + "/v1/solve",
        {"m": 8, "n": 24, "seed": 2, "async": True},
    )
    assert code == 202 and out["href"].startswith("/v1/solve/")
    deadline = time.perf_counter() + 60
    while True:
        code, res = _http(backend.url + out["href"])
        if code != 202 or time.perf_counter() > deadline:
            break
        time.sleep(0.02)
    assert code == 200 and res["status"] == "optimal"
    code, _ = _http(backend.url + "/v1/solve/bogus-id")
    assert code == 404


def test_http_429_with_retry_after(backend):
    # burst=1 at 2/s: the second immediate submit must shed.
    codes = []
    for k in range(2):
        code, out = _http(
            backend.url + "/v1/solve",
            {"m": 8, "n": 24, "seed": 40 + k, "tenant": "limited",
             "async": True},
        )
        codes.append((code, out))
    (c1, _), (c2, o2) = codes
    assert c1 == 202
    assert c2 == 429
    assert o2["reason"] == "quota" and o2["retry_after_s"] > 0


def test_http_deadline_maps_to_504(backend):
    # A microscopic deadline expires while queued -> service TIMEOUT ->
    # HTTP 504 with the solver's verdict in the body.
    code, out = _http(
        backend.url + "/v1/solve",
        {"m": 8, "n": 24, "seed": 77, "deadline_ms": 0.01},
    )
    assert code == 504
    assert out.get("status") in ("timeout", None)


def test_http_metrics_and_statusz(backend):
    _http(backend.url + "/v1/solve", {"m": 8, "n": 24, "seed": 11})
    with urllib.request.urlopen(backend.url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "net_requests_total" in text
    assert "net_inflight" in text
    assert "serve_requests_total" in text  # one registry, whole backend
    code, st = _http(backend.url + "/statusz")
    assert code == 200
    assert st["net"]["requests_total"] >= 1
    assert st["stats"]["requests"] >= 1
    assert "admission" in st["stats"]


def test_healthz_flips_on_device_loss_and_wedge(backend):
    import jax

    from distributedlpsolver_tpu.parallel.runtime import (
        restore_devices,
        simulate_device_loss,
    )

    code, body = _http(backend.url + "/healthz")
    assert code == 200 and body["status"] == "ok"
    try:
        simulate_device_loss([d.id for d in jax.devices()])
        time.sleep(0.05)  # step past the healthz cache
        code, body = _http(backend.url + "/healthz")
        assert code == 503
        assert body["devices_unhealthy"]
    finally:
        restore_devices()
    time.sleep(0.05)
    code, body = _http(backend.url + "/healthz")
    assert code == 200 and body["pipeline_alive"]


# ---------------------------------------------------------------------------
# router tier


def _mk_backend(reg=None):
    reg = reg or MetricsRegistry()
    svc = SolveService(
        ServiceConfig(batch=4, flush_s=0.02, max_queue_depth=64),
        metrics=reg,
    )
    front = SolveHTTPServer(
        svc, NetConfig(healthz_cache_s=0.02), metrics=reg
    ).start()
    return svc, front


def test_router_routes_and_spreads_load():
    svcs_fronts = [_mk_backend() for _ in range(2)]
    router = Router(
        [f.url for _, f in svcs_fronts],
        RouterConfig(poll_s=0.1),
        metrics=MetricsRegistry(),
    ).start()
    rhttp = RouterHTTPServer(router).start()
    try:
        for k in range(8):
            code, out = _http(
                rhttp.url + "/v1/solve", {"m": 8, "n": 24, "seed": k}
            )
            assert code == 200 and out["status"] == "optimal"
        st = router.statusz()
        forwards = [b["forwards"] for b in st["backends"]]
        assert sum(forwards) == 8
        assert all(f > 0 for f in forwards)  # both backends saw traffic
    finally:
        rhttp.shutdown()
        router.shutdown()
        for svc, front in svcs_fronts:
            front.shutdown()
            svc.shutdown()


def test_router_shape_aware_pick_prefers_tight_bucket():
    r = Router.__new__(Router)  # scoring is pure — no live backends
    assert Router._padding_score(8, 24, [(8, 24, 8)]) == 0.0
    loose = Router._padding_score(8, 24, [(16, 32, 8)])
    assert 0 < loose < 1
    assert Router._padding_score(100, 400, [(8, 24, 8)]) == 1.0


def test_router_passes_solver_timeout_504_without_eject():
    """A backend's own 504 — the solver TIMEOUT verdict for a request
    whose deadline expired while queued — is a normal SLO-shedding
    outcome, NOT failover evidence: the router must pass it through
    without ejecting the (healthy) backend or retrying the solve on a
    second one (which would duplicate load under exactly the deadline
    storms that produce these)."""
    svcs_fronts = [_mk_backend() for _ in range(2)]
    router = Router(
        [f.url for _, f in svcs_fronts],
        RouterConfig(poll_s=0.1),
        metrics=MetricsRegistry(),
    ).start()
    rhttp = RouterHTTPServer(router).start()
    try:
        code, out = _http(
            rhttp.url + "/v1/solve",
            {"m": 8, "n": 24, "seed": 55, "deadline_ms": 0.01},
        )
        assert code == 504 and out.get("status") == "timeout"
        st = router.statusz()
        assert st["failovers"] == 0
        assert all(not b["ejected"] for b in st["backends"])
        assert router.healthy_count() == 2
        # The rotation still serves: a normal request lands 200.
        code, out = _http(
            rhttp.url + "/v1/solve", {"m": 8, "n": 24, "seed": 56}
        )
        assert code == 200 and out["status"] == "optimal"
    finally:
        rhttp.shutdown()
        router.shutdown()
        for svc, front in svcs_fronts:
            front.shutdown()
            svc.shutdown()


def test_router_failover_no_request_lost():
    """Kill a backend mid-stream: every request still completes via the
    retry-once failover; the dead backend is ejected and the survivor
    carries the tail."""
    svcs_fronts = [_mk_backend() for _ in range(2)]
    router = Router(
        [f.url for _, f in svcs_fronts],
        RouterConfig(poll_s=0.5),
        metrics=MetricsRegistry(),
    ).start()
    rhttp = RouterHTTPServer(router).start()
    results = []
    try:
        for k in range(20):
            if k == 6:  # mid-stream kill, no drain
                svcs_fronts[1][1].shutdown()
            code, out = _http(
                rhttp.url + "/v1/solve", {"m": 8, "n": 24, "seed": 200 + k}
            )
            results.append((code, out.get("status")))
        assert all(c == 200 and s == "optimal" for c, s in results)
        st = router.statusz()
        dead = next(
            b for b in st["backends"] if b["url"] == svcs_fronts[1][1].url
        )
        assert dead["ejected"]
        code, body = _http(rhttp.url + "/healthz")
        assert code == 200 and body["healthy_backends"] == 1
    finally:
        rhttp.shutdown()
        router.shutdown()
        svcs_fronts[0][1].shutdown()
        for svc, _ in svcs_fronts:
            svc.shutdown()


def test_router_recovers_backend_on_health_return():
    svc, front = _mk_backend()
    router = Router(
        [front.url, "http://127.0.0.1:1"],  # second is never alive
        RouterConfig(poll_s=0.05, eject_after=1),
        metrics=MetricsRegistry(),
    ).start()
    try:
        time.sleep(0.2)
        assert router.healthy_count() == 1
        # Device loss flips the live backend's healthz -> ejected...
        import jax

        from distributedlpsolver_tpu.parallel.runtime import (
            restore_devices,
            simulate_device_loss,
        )

        try:
            simulate_device_loss([d.id for d in jax.devices()])
            deadline = time.perf_counter() + 10
            while router.healthy_count() > 0:
                assert time.perf_counter() < deadline, "never ejected"
                time.sleep(0.05)
        finally:
            restore_devices()
        # ... and recovery re-admits it without a restart.
        deadline = time.perf_counter() + 10
        while router.healthy_count() < 1:
            assert time.perf_counter() < deadline, "never re-admitted"
            time.sleep(0.05)
    finally:
        router.shutdown()
        front.shutdown()
        svc.shutdown()


def test_router_metrics_and_events(tmp_path):
    log = tmp_path / "router.jsonl"
    svc, front = _mk_backend()
    reg = MetricsRegistry()
    router = Router(
        [front.url],
        RouterConfig(poll_s=0.1, log_jsonl=str(log)),
        metrics=reg,
    ).start()
    rhttp = RouterHTTPServer(router, metrics=reg).start()
    try:
        code, _ = _http(rhttp.url + "/v1/solve", {"m": 8, "n": 24, "seed": 9})
        assert code == 200
        front.shutdown()  # now kill it and watch the ejection land
        code, _ = _http(rhttp.url + "/v1/solve", {"m": 8, "n": 24, "seed": 10})
        assert code in (502, 503)  # single backend: nothing to fail over to
        with urllib.request.urlopen(rhttp.url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "router_backend_healthy" in text
        assert "router_routed_total" in text
    finally:
        rhttp.shutdown()
        router.shutdown()
        svc.shutdown()
    events = [json.loads(l) for l in log.read_text().splitlines()]
    kinds = {e["event"] for e in events}
    assert "route" in kinds and "backend_ejected" in kinds
    route = next(e for e in events if e["event"] == "route")
    assert route["m"] == 8 and route["backend"] == front.url
    assert all("ts" in e and "schema_version" in e for e in events)


# ---------------------------------------------------------------------------
# CLI


def test_cli_serve_surfaces_admission_and_backoffs(tmp_path, capsys):
    """The cli serve overload path uses the admission verdict's wait
    hint and surfaces rejects in the summary (satellite fix)."""
    from distributedlpsolver_tpu.cli import main

    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text(
        "".join(
            json.dumps(
                {"m": 8, "n": 24, "seed": s, "id": f"r{s}",
                 "tenant": "only", "priority": "normal"}
            ) + "\n"
            for s in range(12)
        )
    )
    out = tmp_path / "res.jsonl"
    quotas = json.dumps(
        {"tenants": {"only": {"rate": 200.0, "burst": 2.0}}}
    )
    rc = main(
        [
            "serve", "--requests", str(reqs), "--out", str(out),
            "--batch", "4", "--flush-ms", "5", "--quotas", quotas,
        ]
    )
    assert rc == 0
    records = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(records) == 12
    assert all(r["status"] == "optimal" for r in records)
    assert all(r["tenant"] == "only" for r in records)
    summary = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
    # burst 2 at 200/s against 12 fast submits: the reader must have
    # been shed at least once, and the summary says so (both sides).
    assert summary["submit_backoffs"] >= 1
    assert summary["admission"]["only"]["rejected"].get("quota", 0) >= 1


def test_cli_route_requires_backend_or_registry(tmp_path):
    from distributedlpsolver_tpu.cli import main

    # No --backend and no --registry: nothing could ever enter rotation.
    assert main(["route"]) == 2
    # With a shared registry the table may start EMPTY — slices
    # self-register and the router adopts them (README "Multi-host");
    # constructing the Router must not raise.
    from distributedlpsolver_tpu.net.router import Router, RouterConfig
    from distributedlpsolver_tpu.obs.metrics import MetricsRegistry

    Router(
        [],
        RouterConfig(registry_path=str(tmp_path / "reg.json")),
        metrics=MetricsRegistry(),
    )
    with pytest.raises(ValueError):
        Router([], RouterConfig(), metrics=MetricsRegistry())


# ---------------------------------------------------------------------------
# crash-safe fabric: readyz / drain endpoint / store eviction / backoff


def test_readyz_ready_then_flips_on_drain(backend):
    code, out = _http(backend.url + "/readyz")
    assert code == 200 and out["status"] == "ready"
    backend.service.begin_draining()
    code, out = _http(backend.url + "/readyz")
    assert code == 503 and out["draining"] is True
    # Liveness is a separate axis: healthz stays 200 while draining.
    code, _ = _http(backend.url + "/healthz")
    assert code == 200
    # Submits shed with the structured draining verdict (503, not 429).
    code, out = _http(
        backend.url + "/v1/solve", {"m": 8, "n": 24, "seed": 1}
    )
    assert code == 503 and out["reason"] == "draining"


def test_quitquitquit_drains_resolves_and_closes_listener():
    reg = MetricsRegistry()
    svc = SolveService(
        ServiceConfig(batch=4, flush_s=0.02), metrics=reg
    )
    front = SolveHTTPServer(
        svc, NetConfig(healthz_cache_s=0.02), metrics=reg
    ).start()
    url = front.url
    try:
        futs = [
            svc.submit(random_dense_lp(8, 24, seed=k)) for k in range(6)
        ]
        code, out = _http(url + "/quitquitquit", {})
        assert code == 200 and out["draining"] is True
        # Idempotent: a second call acknowledges without a second drain.
        code, out2 = _http(url + "/quitquitquit", {})
        assert code in (200, 599) and (
            code != 200 or out2.get("started") in (False, True)
        )
        # Every accepted request resolves (graceful, not dropped).
        assert all(
            f.result(timeout=120).status is Status.OPTIMAL for f in futs
        )
        # The listener closes only AFTER the drain.
        deadline = time.monotonic() + 60
        closed = False
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(url + "/healthz", timeout=2)
            except (urllib.error.URLError, OSError):
                closed = True
                break
            time.sleep(0.05)
        assert closed, "listener never closed after the drain"
    finally:
        svc.shutdown(drain=False)
        front.shutdown()


def test_async_store_evicts_resolved_only_with_metric():
    """The PR's small fix: under cap pressure the async store must
    never drop an unresolved entry (that silently loses an acknowledged
    poll URL); evictions take resolved entries and count into
    net_store_evictions_total{state}."""
    from concurrent.futures import Future

    reg = MetricsRegistry()
    svc = SolveService(ServiceConfig(batch=4, flush_s=0.02), metrics=reg)
    front = SolveHTTPServer(
        svc, NetConfig(async_results_cap=4), metrics=reg
    )
    try:
        pending = [Future() for _ in range(4)]
        rids_pending = [front._register_async(f, True) for f in pending]
        # 4 unresolved at cap: a 5th (resolved) entry must not evict
        # any pending future.
        done = Future()
        done.set_result("r")
        rid_done = front._register_async(done, True)
        assert all(
            front._lookup_async(r) is not None for r in rids_pending
        )
        # More resolved entries: eviction now takes the RESOLVED ones.
        done2 = Future()
        done2.set_result("r2")
        front._register_async(done2, True)
        assert front._lookup_async(rid_done) is None  # oldest resolved
        assert all(
            front._lookup_async(r) is not None for r in rids_pending
        )
        snap = reg.snapshot()
        assert (
            snap.get('net_store_evictions_total{state="resolved"}', 0) >= 1
        )
        assert (
            snap.get('net_store_evictions_total{state="unresolved"}', 0)
            == 0
        )
    finally:
        svc.shutdown(drain=False)
        front.shutdown()


def test_router_probe_backoff_exponential_and_resets():
    """Ejected backends are re-probed with exponential, deterministically
    jittered backoff capped at the config ceiling — not hammered every
    poll tick."""
    cfg = RouterConfig(
        poll_s=0.05, eject_after=1,
        probe_backoff_base_s=0.2, probe_backoff_cap_s=1.0,
    )
    router = Router(["http://127.0.0.1:9"], cfg, metrics=MetricsRegistry())
    try:
        backoffs = []
        for _ in range(6):
            router.poll_once()
            st = router.statusz()["backends"][0]
            backoffs.append(st["backoff_s"])
            with router._lock:
                router._backends[st["url"]].next_probe = 0.0  # force re-probe
        assert router.statusz()["backends"][0]["ejected"]
        grown = [b for b in backoffs if b > 0]
        assert grown and grown == sorted(grown)  # monotone growth
        assert max(grown) <= cfg.probe_backoff_cap_s
        # Deterministic: the same (url, fails) sequence reproduces.
        router2 = Router(
            ["http://127.0.0.1:9"], cfg, metrics=MetricsRegistry()
        )
        for _ in range(6):
            router2.poll_once()
            with router2._lock:
                router2._backends["http://127.0.0.1:9"].next_probe = 0.0
        assert (
            router2.statusz()["backends"][0]["backoff_s"]
            == router.statusz()["backends"][0]["backoff_s"]
        )
        router2.shutdown()
        # Backoff actually paces: with next_probe in the future the
        # sweep skips the backend entirely.
        with router._lock:
            st = router._backends["http://127.0.0.1:9"]
            st.next_probe = time.perf_counter() + 60
            probes_before = st.probes
        router.poll_once()
        with router._lock:
            assert (
                router._backends["http://127.0.0.1:9"].probes
                == probes_before
            )
    finally:
        router.shutdown()


def test_router_stops_routing_to_draining_backend_without_eject():
    reg = MetricsRegistry()
    svc = SolveService(ServiceConfig(batch=4, flush_s=0.02), metrics=reg)
    front = SolveHTTPServer(
        svc, NetConfig(healthz_cache_s=0.02), metrics=reg
    ).start()
    router = Router(
        [front.url], RouterConfig(poll_s=0.05), metrics=MetricsRegistry()
    ).start()
    try:
        assert router.healthy_count() == 1
        svc.begin_draining()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = router.statusz()["backends"][0]
            if not st["ready"]:
                break
            time.sleep(0.05)
        st = router.statusz()["backends"][0]
        # Not ready (out of rotation) but NOT ejected: healthy, alive.
        assert st["ready"] is False
        assert st["ejected"] is False and st["healthy"] is True
        assert router.pick() is None  # nothing routable
        code, _, url = router.forward(
            "/v1/solve",
            json.dumps({"m": 8, "n": 24, "seed": 1}).encode(),
            "application/json",
        )
        assert code == 503 and url is None
    finally:
        router.shutdown()
        front.shutdown()
        svc.shutdown(drain=False)


def test_replicated_routers_share_ejections_via_registry(tmp_path):
    """An ejection observed by one router is honored by its sibling
    through the shared registry — and a restarted router warm-loads
    the table instead of starting blind."""
    rpath = str(tmp_path / "registry.json")
    reg_cfg = RouterConfig(poll_s=30.0, registry_path=rpath)
    r1 = Router(["http://127.0.0.1:9"], reg_cfg, metrics=MetricsRegistry())
    r2 = Router(["http://127.0.0.1:9"], reg_cfg, metrics=MetricsRegistry())
    try:
        # r1 observes a forward failure -> ejects + publishes.
        r1._note_forward_failure("http://127.0.0.1:9")
        assert r1.statusz()["backends"][0]["ejected"]
        # r2 adopts it on its next registry pull, without probing.
        r2._sync_registry_pull()
        assert r2.statusz()["backends"][0]["ejected"]
        # A restarted router (fresh process, same registry) warm-loads
        # the ejected state instead of routing into a dead backend.
        r3 = Router([], reg_cfg, metrics=MetricsRegistry())
        st = r3.statusz()["backends"][0]
        assert st["url"] == "http://127.0.0.1:9" and st["ejected"]
        assert r3.pick() is None
        r3.shutdown()
        # Generation advanced and the registry surface is reported.
        assert r1.statusz()["registry"]["generation"] >= 1
    finally:
        r1.shutdown()
        r2.shutdown()


# tier-1 smoke: the full 200-request router/2-backend probe


def test_probe_net_smoke():
    """CI satellite: the network-plane acceptance probe (200 HTTP
    requests, 2 tenants, router over 2 backends, mid-run kill, metrics/
    healthz validity) runs on every tier-1 pass under a wall budget."""
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "probe_net.py"),
         "--requests", "200", "--budget-s", "240"],
        capture_output=True, text=True, timeout=400,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout
    assert time.perf_counter() - t0 < 400
