"""Infeasibility/unboundedness detection (divergence heuristics)."""

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.problem import LPProblem

INF = np.inf


def _infeasible_lp():
    # x1 + x2 = 2  AND  x1 + x2 <= 1, x >= 0
    return LPProblem(
        c=[1.0, 1.0],
        A=np.array([[1.0, 1.0], [1.0, 1.0]]),
        rlb=[2.0, -INF],
        rub=[2.0, 1.0],
        lb=[0.0, 0.0],
        ub=[INF, INF],
        name="infeasible",
    )


def _unbounded_lp():
    # min -x1, x1 - x2 = 0, x >= 0 → ray (t, t)
    return LPProblem(
        c=[-1.0, 0.0],
        A=np.array([[1.0, -1.0]]),
        rlb=[0.0],
        rub=[0.0],
        lb=[0.0, 0.0],
        ub=[INF, INF],
        name="unbounded",
    )


@pytest.mark.parametrize("fused", [True, False])
def test_infeasible_detected(fused):
    r = solve(_infeasible_lp(), backend="tpu", max_iter=100, fused_loop=fused)
    assert r.status in (Status.PRIMAL_INFEASIBLE, Status.ITERATION_LIMIT, Status.NUMERICAL_ERROR)
    assert r.status != Status.OPTIMAL


@pytest.mark.parametrize("fused", [True, False])
def test_unbounded_detected(fused):
    r = solve(_unbounded_lp(), backend="tpu", max_iter=100, fused_loop=fused)
    assert r.status in (Status.DUAL_INFEASIBLE, Status.ITERATION_LIMIT, Status.NUMERICAL_ERROR)
    assert r.status != Status.OPTIMAL


def test_infeasible_gets_specific_status():
    """The divergence heuristic should fire, not just hit the iteration cap."""
    r = solve(_infeasible_lp(), backend="tpu", max_iter=200)
    assert r.status == Status.PRIMAL_INFEASIBLE, r.summary()


def test_unbounded_gets_specific_status():
    r = solve(_unbounded_lp(), backend="tpu", max_iter=200)
    assert r.status == Status.DUAL_INFEASIBLE, r.summary()


class TestCertificates:
    """Farkas-ray extraction (ipm/certificates.py): the heuristic verdicts
    get upgraded to checkable certificates."""

    def test_infeasible_yields_certified_farkas_ray(self):
        r = solve(_infeasible_lp(), backend="tpu", max_iter=200)
        assert r.status == Status.PRIMAL_INFEASIBLE
        c = r.certificate
        assert c is not None and c.kind == "primal_infeasible"
        assert c.certified, c.summary()
        assert c.separation > 0
        # check the certificate independently: for the interior form the
        # driver solved, A^T y - z <= tol and b@y - u@z = separation > 0
        assert c.violation <= 1e-6 * max(1.0, c.separation)

    def test_unbounded_yields_certified_ray(self):
        r = solve(_unbounded_lp(), backend="tpu", max_iter=200)
        assert r.status == Status.DUAL_INFEASIBLE
        c = r.certificate
        assert c is not None and c.kind == "dual_infeasible"
        assert c.certified, c.summary()
        assert c.separation > 0

    def test_optimal_has_no_certificate(self):
        from distributedlpsolver_tpu.models.generators import random_dense_lp

        r = solve(random_dense_lp(12, 30, seed=0), backend="cpu")
        assert r.status == Status.OPTIMAL
        assert r.certificate is None

    def test_certificate_checks_directly(self):
        # Hand-checkable instance: rows x1+x2=2 and x1+x2<=1 admit
        # y = (1, -1): A^T y = 0, b@y = 2-1 = 1 > 0.
        import numpy as np
        from distributedlpsolver_tpu.ipm.certificates import (
            primal_infeasibility_certificate,
        )
        from distributedlpsolver_tpu.models.problem import to_interior_form

        inf = to_interior_form(_infeasible_lp())
        cert = primal_infeasibility_certificate(inf, np.array([1.0, -1.0]))
        assert cert is not None and cert.certified
        assert cert.violation <= 1e-12


class TestScaleFreeHeuristics:
    """classify_divergence must be dimensionless: scaling the problem
    data must not flip a feasible verdict to infeasible/unbounded
    (VERDICT round 2, weak item 4 / next item 7)."""

    @pytest.mark.parametrize("factor", [1e-6, 1e6])
    def test_badly_scaled_feasible_is_never_declared_infeasible(self, factor):
        # A feasible, bounded LP with objective and rhs pushed 6 orders
        # of magnitude off unit scale, solved WITHOUT the auto-scaler so
        # the raw magnitudes reach the heuristics. Any terminal status is
        # tolerable except a false infeasibility/unboundedness verdict.
        from distributedlpsolver_tpu.models.generators import random_dense_lp

        p = random_dense_lp(24, 60, seed=11)
        q = LPProblem(
            c=p.c * factor,
            A=p.A,
            rlb=p.rlb * factor,
            rub=p.rub * factor,
            lb=p.lb,
            ub=p.ub,
            name="badscale",
        )
        r = solve(q, backend="tpu", scale=False, max_iter=120)
        assert r.status not in (
            Status.PRIMAL_INFEASIBLE,
            Status.DUAL_INFEASIBLE,
        ), r.summary()

    def test_classify_divergence_is_scale_invariant(self):
        # The heuristic's verdict on a diverging trajectory must be the
        # same at unit scale and with objectives/mu rescaled by 1e8.
        from distributedlpsolver_tpu.ipm import core

        # Farkas-like signature: mu converged, pinf stuck, dual runaway
        base = dict(
            mu=1e-12, pinf=0.1, dinf=1e-9, rel_gap=5.0, pobj=3.0, dobj=1e10
        )
        for s in (1.0, 1e8, 1e-8):
            pin, din = core.classify_divergence(
                base["mu"] * s, base["pinf"], base["dinf"], base["rel_gap"],
                base["pobj"] * s, base["dobj"] * s,
            )
            assert bool(pin) and not bool(din), s

        # Healthy mid-solve iterate at huge objective scale: no verdict.
        pin, din = core.classify_divergence(
            mu=1e2, pinf=1e-5, dinf=1e-6, rel_gap=1e-3,
            pobj=1e10, dobj=1e10 - 1e5,
        )
        assert not bool(pin) and not bool(din)
