"""Infeasibility/unboundedness detection (divergence heuristics)."""

import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.problem import LPProblem

INF = np.inf


def _infeasible_lp():
    # x1 + x2 = 2  AND  x1 + x2 <= 1, x >= 0
    return LPProblem(
        c=[1.0, 1.0],
        A=np.array([[1.0, 1.0], [1.0, 1.0]]),
        rlb=[2.0, -INF],
        rub=[2.0, 1.0],
        lb=[0.0, 0.0],
        ub=[INF, INF],
        name="infeasible",
    )


def _unbounded_lp():
    # min -x1, x1 - x2 = 0, x >= 0 → ray (t, t)
    return LPProblem(
        c=[-1.0, 0.0],
        A=np.array([[1.0, -1.0]]),
        rlb=[0.0],
        rub=[0.0],
        lb=[0.0, 0.0],
        ub=[INF, INF],
        name="unbounded",
    )


@pytest.mark.parametrize("fused", [True, False])
def test_infeasible_detected(fused):
    r = solve(_infeasible_lp(), backend="tpu", max_iter=100, fused_loop=fused)
    assert r.status in (Status.PRIMAL_INFEASIBLE, Status.ITERATION_LIMIT, Status.NUMERICAL_ERROR)
    assert r.status != Status.OPTIMAL


@pytest.mark.parametrize("fused", [True, False])
def test_unbounded_detected(fused):
    r = solve(_unbounded_lp(), backend="tpu", max_iter=100, fused_loop=fused)
    assert r.status in (Status.DUAL_INFEASIBLE, Status.ITERATION_LIMIT, Status.NUMERICAL_ERROR)
    assert r.status != Status.OPTIMAL


def test_infeasible_gets_specific_status():
    """The divergence heuristic should fire, not just hit the iteration cap."""
    r = solve(_infeasible_lp(), backend="tpu", max_iter=200)
    assert r.status == Status.PRIMAL_INFEASIBLE, r.summary()


def test_unbounded_gets_specific_status():
    r = solve(_unbounded_lp(), backend="tpu", max_iter=200)
    assert r.status == Status.DUAL_INFEASIBLE, r.summary()
