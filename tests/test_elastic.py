"""Elastic mesh recovery: device loss → re-formation → re-sharded resume.

The ISSUE acceptance scenario runs here end-to-end on the 8 virtual CPU
devices: a sharded solve loses 2 of its 8 mesh participants mid-solve,
the supervisor re-forms a 6-device mesh over the survivors, re-shards the
problem and the last-good iterate, and converges to the fault-free
objective within 1e-8 — via the SHRINK rung, never the CPU fallback.
Plus the building blocks: mesh re-formation, device health probes,
per-shard hang attribution, and the adaptive watchdog deadline.
"""

import json
import time

import jax
import numpy as np
import pytest

from distributedlpsolver_tpu.ipm import Status, solve
from distributedlpsolver_tpu.models.generators import random_dense_lp
from distributedlpsolver_tpu.parallel import (
    make_mesh,
    probe_devices,
    reform_mesh,
    restore_devices,
    simulate_device_loss,
)
from distributedlpsolver_tpu.supervisor import (
    AdaptiveDeadline,
    FaultKind,
    InjectedFault,
    SupervisorConfig,
    supervised_solve,
)

pytestmark = [
    pytest.mark.elastic,
    pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
    ),
]

_PROBLEM = dict(m=20, n=45, seed=3)


def _problem():
    return random_dense_lp(**_PROBLEM)


def _sup(**kw):
    kw.setdefault("backoff_base", 0.001)
    return SupervisorConfig(**kw)


@pytest.fixture(autouse=True)
def _clean_registry():
    """Injected device loss marks ids in a process-local registry the
    health probe consults; never leak that into the next test."""
    restore_devices()
    yield
    restore_devices()


@pytest.fixture(scope="module")
def reference_result():
    return solve(_problem(), backend="sharded", fused_loop=False)


# -------------------------------------------------------- mesh re-formation
def test_reform_mesh_excludes_devices():
    mesh = make_mesh()
    lost = [d.id for d in mesh.devices.flat][-2:]
    smaller = reform_mesh(mesh, exclude=lost)
    assert smaller.devices.size == mesh.devices.size - 2
    assert smaller.axis_names == ("cols",)
    assert not {d.id for d in smaller.devices.flat} & set(lost)
    # Device objects (not just ids) are accepted too.
    smaller2 = reform_mesh(mesh, exclude=list(mesh.devices.flat)[:1])
    assert smaller2.devices.size == mesh.devices.size - 1


def test_reform_mesh_refuses_empty():
    mesh = make_mesh()
    with pytest.raises(ValueError, match="no devices"):
        reform_mesh(mesh, exclude=[d.id for d in mesh.devices.flat])


def test_reform_mesh_collapses_hybrid_to_1d():
    from distributedlpsolver_tpu.parallel import make_hybrid_mesh

    hybrid = make_hybrid_mesh(ici_parallelism=4, dcn_parallelism=2)
    lost = [d.id for d in hybrid.devices.flat][:1]
    smaller = reform_mesh(hybrid, exclude=lost)
    # 7 survivors cannot tile (2, ici); the re-formed mesh is 1-D over
    # the innermost (ICI/"cols") axis name.
    assert smaller.devices.shape == (7,)
    assert smaller.axis_names == ("cols",)


# ------------------------------------------------------------ health probes
def test_probe_flags_simulated_loss():
    devs = jax.devices()
    healthy, unhealthy = probe_devices(devs)
    assert [d.id for d in unhealthy] == []
    simulate_device_loss([devs[2].id, devs[5].id])
    healthy, unhealthy = probe_devices(devs)
    assert sorted(d.id for d in unhealthy) == sorted(
        [devs[2].id, devs[5].id]
    )
    assert len(healthy) == len(devs) - 2
    restore_devices([devs[2].id])
    _, unhealthy = probe_devices(devs)
    assert [d.id for d in unhealthy] == [devs[5].id]


# ------------------------------------------- the acceptance scenario (8→6)
def test_device_loss_shrinks_mesh_and_converges(reference_result):
    """Injected loss of 2 of 8 devices: the solve completes via mesh
    re-formation — SHRINK in the fault history, still on the sharded
    backend (no cpu fallback) — and matches the fault-free objective
    within 1e-8."""
    devs = jax.devices()
    lost = (devs[5].id, devs[6].id)
    plan = [
        InjectedFault(FaultKind.DEVICE_LOST, iteration=3, device_ids=lost)
    ]
    r = supervised_solve(
        _problem(),
        backend="sharded",
        supervisor=_sup(fault_plan=plan),
    )
    assert r.status == Status.OPTIMAL
    assert r.backend == "sharded"  # recovered the mesh, not the CPU
    assert [f.kind for f in r.faults] == [FaultKind.DEVICE_LOST]
    f = r.faults[0]
    assert f.action == "shrink:8->6"
    assert sorted(f.devices) == sorted(lost)
    assert f.recovery_overhead_s > 0.0  # resume landed and was timed
    assert abs(r.objective - reference_result.objective) <= 1e-8 * (
        1.0 + abs(reference_result.objective)
    )


def test_device_loss_below_min_devices_degrades():
    """With min_devices above the survivor count the SHRINK rung is
    gated off and the ladder falls through to backend degradation."""
    devs = jax.devices()
    plan = [
        InjectedFault(
            FaultKind.DEVICE_LOST, iteration=2, device_ids=(devs[1].id,)
        )
    ]
    r = supervised_solve(
        _problem(),
        backend="sharded",
        supervisor=_sup(fault_plan=plan, min_devices=8),
    )
    assert r.status == Status.OPTIMAL
    assert r.faults[0].action == "degrade:tpu"
    assert r.backend == "tpu"


def test_persistent_shard_hang_attributed_and_shrunk(reference_result):
    """'Shard k always hangs': two watchdog timeouts both attributed to
    the same device by the health probe promote it to DEVICE_LOST-class
    recovery — the mesh shrinks it out and the solve completes on 7.
    The deadline is ADAPTIVE (a static one sized for the hang would
    false-fire on the compiling first step; the warm-up grace plus
    10×-median is the mechanism that makes this scenario decidable)."""
    shard = jax.devices()[3].id
    plan = [
        InjectedFault(
            FaultKind.HANG,
            iteration=4,
            shard=shard,
            times=None,  # hangs EVERY time its device is in the mesh
            hang_seconds=30.0,
        )
    ]
    t0 = time.perf_counter()
    r = supervised_solve(
        _problem(),
        backend="sharded",
        supervisor=_sup(
            fault_plan=plan,
            adaptive_timeout=True,
            timeout_floor=0.3,
            timeout_warmup=3,
            hang_shard_threshold=2,
            max_retries=8,
        ),
    )
    elapsed = time.perf_counter() - t0
    assert r.status == Status.OPTIMAL
    assert r.backend == "sharded"
    kinds = [f.kind for f in r.faults]
    assert kinds == [FaultKind.HANG, FaultKind.HANG]
    assert r.faults[0].action == "rollback"  # below the threshold
    assert r.faults[1].action == "shrink:8->7"
    assert r.faults[1].devices == (shard,)
    # The watchdog abandoned both 30 s hangs — the wall clock holds
    # compiles and warm steps, never a slept-out nap.
    assert elapsed < 55.0
    assert abs(r.objective - reference_result.objective) <= 1e-6 * (
        1.0 + abs(reference_result.objective)
    )


def test_fault_and_resume_events_in_jsonl(tmp_path):
    """The telemetry stream carries the fault classification and the
    resume completion with its recovery overhead, interleaved with the
    per-iteration records of every attempt (append mode)."""
    devs = jax.devices()
    log = tmp_path / "telemetry.jsonl"
    plan = [
        InjectedFault(
            FaultKind.DEVICE_LOST, iteration=3, device_ids=(devs[7].id,)
        )
    ]
    r = supervised_solve(
        _problem(),
        backend="sharded",
        supervisor=_sup(fault_plan=plan),
        log_jsonl=str(log),
    )
    assert r.status == Status.OPTIMAL
    records = [json.loads(l) for l in log.read_text().splitlines()]
    events = [rec for rec in records if "event" in rec]
    iters = [rec for rec in records if "event" not in rec]
    fault_ev = [e for e in events if e["event"] == "fault"]
    resume_ev = [e for e in events if e["event"] == "resume"]
    assert len(fault_ev) == 1 and len(resume_ev) == 1
    assert fault_ev[0]["kind"] == "device_lost"
    assert fault_ev[0]["action"] == "shrink:8->7"
    assert fault_ev[0]["devices"] == [devs[7].id]
    assert resume_ev[0]["recovery_overhead_s"] > 0.0
    assert resume_ev[0]["recovery_overhead_s"] == pytest.approx(
        r.faults[0].recovery_overhead_s, abs=1e-6
    )
    # Pre-fault iterations (attempt 1) were not truncated by the retry.
    assert [rec["iter"] for rec in iters][:2] == [1, 2]


# ------------------------------------------------------- adaptive deadline
class TestAdaptiveDeadline:
    def test_warmup_grace_uses_static_hint(self):
        ad = AdaptiveDeadline(warmup=3, static_hint=42.0)
        assert ad.current() == 42.0  # no observations yet
        ad.observe(0.1)
        ad.observe(0.1)
        assert ad.current() == 42.0  # still inside warm-up
        ad.observe(0.1)
        assert ad.current() == pytest.approx(1.0)  # 10× median, floored

    def test_warmup_without_hint_means_no_deadline(self):
        ad = AdaptiveDeadline(warmup=2)
        assert ad.current() is None
        ad.observe(30.0)  # the compile step
        assert ad.current() is None
        ad.observe(0.5)
        assert ad.current() is not None

    def test_tracks_trailing_median_not_outliers(self):
        ad = AdaptiveDeadline(warmup=0, floor=0.0, window=8)
        for _ in range(7):
            ad.observe(0.2)
        ad.observe(50.0)  # one GC-pause outlier must not ratchet it up
        assert ad.current() == pytest.approx(10.0 * 0.2)

    def test_window_is_trailing(self):
        ad = AdaptiveDeadline(warmup=0, floor=0.0, ceiling=1e9, window=4)
        for _ in range(10):
            ad.observe(1.0)
        for _ in range(4):
            ad.observe(3.0)  # old regime fully evicted
        assert ad.current() == pytest.approx(30.0)
        assert ad.observations == 4

    def test_floor_and_ceiling_clamp(self):
        ad = AdaptiveDeadline(warmup=0, floor=0.5, ceiling=100.0)
        ad.observe(1e-4)
        assert ad.current() == 0.5
        ad2 = AdaptiveDeadline(warmup=0, floor=0.5, ceiling=100.0)
        ad2.observe(1e4)
        assert ad2.current() == 100.0

    def test_grace_reopens_without_losing_history(self):
        ad = AdaptiveDeadline(warmup=2, floor=0.0, static_hint=None)
        ad.observe(0.1)
        ad.observe(0.1)
        assert ad.current() == pytest.approx(1.0)
        ad.grant_grace()  # post-shrink recompile headroom
        assert ad.current() is None
        ad.observe(5.0)  # the recompile step — absorbed by the median
        ad.observe(0.1)
        assert ad.current() == pytest.approx(1.0)

    def test_reset_forgets_regime(self):
        ad = AdaptiveDeadline(warmup=1, static_hint=7.0)
        ad.observe(0.1)
        assert ad.current() is not None
        ad.reset()
        assert ad.observations == 0
        assert ad.current() == 7.0  # back to the static warm-up fallback

    def test_invalid_params_rejected(self):
        with pytest.raises(ValueError):
            AdaptiveDeadline(multiplier=1.0)
        with pytest.raises(ValueError):
            AdaptiveDeadline(floor=10.0, ceiling=1.0)


def test_adaptive_supervised_solve_catches_injected_hang():
    """End-to-end: no static deadline at all — the adaptive tracker
    learns the CPU step cadence and its 10×-median deadline still
    catches an injected hang (which a 30 s static default would have
    slept through)."""
    plan = [InjectedFault(FaultKind.HANG, iteration=6, hang_seconds=30.0)]
    t0 = time.perf_counter()
    r = supervised_solve(
        _problem(),
        backend="cpu",
        supervisor=_sup(
            fault_plan=plan,
            adaptive_timeout=True,
            timeout_floor=0.2,
            timeout_warmup=2,
        ),
    )
    elapsed = time.perf_counter() - t0
    assert r.status == Status.OPTIMAL
    assert [f.kind for f in r.faults] == [FaultKind.HANG]
    assert elapsed < 20.0  # nothing slept out the 30 s injected hang
