# Seeds: jit-nonhoisted (x2), jit-scalar-default, jit-donate.
# Checked with pkg_path="backends/batched.py" so the donate catalogue
# entry for _batched_segment_jit applies.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("params",))
def _batched_segment_jit(A, carry, params, scale=2.0):
    # scale=2.0 is a traced scalar default -> jit-scalar-default
    # missing donate_argnums -> jit-donate
    return carry * scale


def per_call_wrapper(v):
    # a fresh jit per call -> jit-nonhoisted
    return jax.jit(lambda x: (x * x).sum())(v)


def nested_decorator(v):
    @jax.jit  # defined per call of nested_decorator -> jit-nonhoisted
    def inner(x):
        return x + 1

    return inner(v)
