# Compliant twin of fx_dtype_bad: dtypes pinned (kwarg or the repo's
# positional short form), passthrough asarray of an array value, index
# arange, and narrowing absent. Same pkg_path="ipm/fx.py".
import jax.numpy as jnp


def build(x, dt):
    a = jnp.zeros((4, 4), jnp.float64)
    b = jnp.asarray(0.5, dtype=dt)
    c = jnp.full((2,), 1.0, dt)
    d = jnp.asarray(x)  # passthrough: inherits x.dtype, exempt
    e = jnp.arange(4)  # index arithmetic, exempt by convention
    f = x.astype(jnp.float64)  # widening is never flagged
    return a, b, c, d, e, f
