# Compliant twin of fx_scenario_bad: the Schur batch program is hoisted
# to module level, the pad buffers pin their dtypes, and the scenario
# record carries only catalogued fields (n_scenarios / scenario_bucket /
# schur_ms / link_ms — analysis/config.JSONL_FIELDS). Checked with
# pkg_path="backends/scenario_fx.py".
import jax
import jax.numpy as jnp


@jax.jit
def _schur_chunk_jit(W, dK):
    return jnp.einsum("kmn,kn,kpn->kmp", W, dK, W)


def schur_chunk(W, dK):
    return _schur_chunk_jit(W, dK)


def pad_lanes(k_pad, mb, nb):
    W = jnp.zeros((k_pad, mb, nb), jnp.float64)
    rowmask = jnp.ones((k_pad, mb), jnp.float64)
    return W, rowmask


def scenario_record(logger, n_scenarios, schur_ms):
    logger.event(
        {
            "event": "request",
            "n_scenarios": n_scenarios,
            "scenario_bucket": 8,
            "schur_ms": schur_ms,
            "link_ms": 0.5,
        }
    )
