# Compliant twin of fx_df32_bad: the IDENTICAL pack narrowing is exempt
# when it lives in the sanctioned two-float module — checked with
# pkg_path="ops/df32.py" (analysis/config.NARROW_SANCTIONED). Constructors
# still pin dtypes (dtype-explicit applies everywhere in ops/).
import jax.numpy as jnp

f32 = jnp.float32


def pack_pair(x):
    hi = x.astype(jnp.float32)  # sanctioned: this IS the df32 engine
    lo = (x - hi.astype(jnp.float64)).astype(f32)
    return hi, lo


def const_pair(n):
    return jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32)
