# Seeds: dtype-explicit x3 (constructor, literal asarray, full) and
# dtype-narrow x2. Checked with pkg_path="ipm/fx.py" (in scope, not a
# sanctioned narrowing module).
import jax.numpy as jnp

f32 = jnp.float32


def build(x):
    a = jnp.zeros((4, 4))  # dtype-explicit
    b = jnp.asarray(0.5)  # dtype-explicit (literal mints the dtype)
    c = jnp.full((2,), 1.0)  # dtype-explicit
    d = x.astype(jnp.float32)  # dtype-narrow
    e = x.astype(f32)  # dtype-narrow
    return a, b, c, d, e
