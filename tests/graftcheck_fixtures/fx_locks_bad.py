# Seeds: guarded-by x3 (unguarded read, unguarded write, wrong lock).
import threading


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._span_lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._results = []  # guarded-by: _lock
        self._spans = []  # guarded-by: _span_lock

    def unguarded_read(self):
        return len(self._results)  # guarded-by violation

    def unguarded_write(self, r):
        self._results = list(r)  # guarded-by violation (store)

    def wrong_lock(self):
        with self._span_lock:
            return list(self._results)  # guarded-by violation
