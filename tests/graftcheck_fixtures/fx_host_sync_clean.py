# Compliant twin of fx_host_sync_bad: the hot-scope bodies stay on the
# host side of the pipeline (no device fetches), float() of a literal is
# host arithmetic, and the one sanctioned sync carries its annotation.
import jax
import numpy as np


class SolveService:
    def _run_solve(self, res, k):
        v = float("nan")  # literal: host arithmetic, not a fetch
        jax.block_until_ready(res)  # graftcheck: disable=host-sync (demux)
        return v

    def _pack_bucket(self, batch):
        return np.zeros((4, 4))  # host construction, not a sync
