"""Clean twin of fx_deadlock_bad.py (pkg_path serve/fx.py): one global
acquisition order (a before b, everywhere, including through calls) and
the blocking round-trip moved outside the lock."""

import threading
import urllib.request


class Pipeline:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def pack(self):
        with self._a:
            self._note()

    def _note(self):
        with self._b:
            pass

    def solve(self):
        # Same a -> b order as pack(): the graph stays acyclic.
        with self._a:
            with self._b:
                pass

    def push(self, payload):
        with self._a:
            body = self._render(payload)
        urllib.request.urlopen("http://example/submit", body)

    def _render(self, payload):
        return payload
