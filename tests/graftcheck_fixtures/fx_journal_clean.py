# Compliant twin of fx_journal_bad: the crash-safe fabric's three new
# event types with catalogued fields only, and the WAL append routed
# through stamp_record (exactly what serve/journal.py does).
import json

from distributedlpsolver_tpu.utils.logging import stamp_record


def emit(logger, wal, rec):
    logger.event(
        {
            "event": "journal_replay",
            "replayed": 3,
            "reenqueued": 2,
            "expired": 1,
            "torn": 1,
            "skipped": 0,
            "results": 5,
        }
    )
    logger.event(
        {
            "event": "drain",
            "phase": "begin",
            "queue_depth": 4,
            "inflight": 2,
        }
    )
    logger.event(
        {
            "event": "registry_write",
            "backend": "http://10.0.0.2:8080",
            "ejected": True,
            "fails": 3,
            "generation": 17,
            "writer": "host:123",
        }
    )
    wal.write(json.dumps(stamp_record(rec)) + "\n")
