"""Clean twin of fx_spmd_bad.py (pkg_path distributed/fx.py): the same
shapes written the way the SPMD contract wants them — world-uniform
branches, unconditional collectives, sorted world-visible iteration,
committed placements, and the sanctioned single-device fallback."""

import os

import jax
import jax.numpy as jnp


def world_report(world, stats):
    # Every rank runs the collective; branching on world_size is fine —
    # it is identical on every rank (world-uniform), unlike rank.
    vals = world.allgather(stats)
    if world.world_size > 1:
        return vals
    return [stats]


def gather_all(world, shards):
    # A world-uniform comprehension filter is fine: world_size is the
    # same on every rank, so every rank runs the same collectives.
    return [world.allgather(s) for s in shards if world.world_size > 1]


def replay_dispatches(control, journal_dir):
    # Deterministic replay order on every rank.
    for fname in sorted(os.listdir(journal_dir)):
        control.publish({"f": fname})


def count_dispatches(journal_dir):
    # Order-insensitive consumers never publish iteration order.
    return sum(1 for f in os.listdir(journal_dir) if f.endswith(".npz"))


def warm_world(service, shapes):
    for spec in sorted(set(shapes)):
        service.publish(spec)


def dispatch_bucket(batch, active, cfg, mesh):
    # Committed placement: the mask rides the same batch-axis sharding
    # as the data.
    act = put_global(active, batch_sharding(mesh, 1))
    return solve_bucket(batch, act, cfg, mesh=mesh)


def place_local(active, mesh=None):
    # The single-device fallback: a bare put is exactly right when the
    # mesh is absent.
    if mesh is None:
        act = jnp.asarray(active)
    else:
        act = jax.device_put(active, batch_sharding(mesh, 1))
    return act


def put_global(x, sharding):
    return x


def batch_sharding(mesh, ndim):
    return None


def solve_bucket(batch, active, cfg, mesh=None):
    return batch
