# Seeds: jsonl-schema x2 — tail-tolerance telemetry written wrong.
# Checked with pkg_path="net/fx.py": a hedge resolution under a type
# the event catalogue never heard of (invisible to `cli report` and the
# probe's ledger reconciliation), and a cancellation carrying an
# uncatalogued verdict field.


def hedge_record(logger, backend, primary):
    logger.event(
        {
            "event": "speculative_retry",  # jsonl-event-type: not catalogued
            "backend": backend,
            "primary": primary,
            "outcome": "hedge_won",
        }
    )


def cancel_record(logger, backend, jid):
    logger.event(
        {
            "event": "cancel",
            "backend": backend,
            "jid": jid,
            "verdict_state": "cancelled",  # jsonl-fields: not catalogued
        }
    )
