# Compliant twin of fx_multihost_bad: the dispatch counter is read
# under its annotated lock, and the world_reinit / heartbeat records
# carry only catalogued fields (generation / world_size / slice_id /
# recovery_overhead_s / rank — analysis/config.JSONL_FIELDS). Checked
# with pkg_path="distributed/fx.py".
import threading


class SliceState:
    def __init__(self):
        self._lock = threading.Lock()
        self.dispatches = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.dispatches += 1

    def snapshot(self):
        with self._lock:
            return self.dispatches


def reinit_record(logger, generation, overhead_s):
    logger.event(
        {
            "event": "world_reinit",
            "generation": generation,
            "world_size": 3,
            "slice_id": "slice0",
            "recovery_overhead_s": overhead_s,
        }
    )


def heartbeat_record(logger, rank):
    logger.event(
        {
            "event": "heartbeat",
            "rank": rank,
            "slice_id": "slice0",
        }
    )
