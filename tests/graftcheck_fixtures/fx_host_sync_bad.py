# Seeds: host-sync x4 (float / .item / np.asarray / block_until_ready),
# one of them inside a nested closure. Checked with
# pkg_path="serve/service.py" so the SolveService hot scopes apply.
import jax
import numpy as np


class SolveService:
    def _run_solve(self, res, k):
        v = float(res[k])  # host-sync
        w = res.item()  # host-sync
        return v + w

    def _pack_bucket(self, batch):
        jax.block_until_ready(batch)  # host-sync

        def helper():
            return np.asarray(batch)  # host-sync (closure on hot thread)

        return helper()

    def cold_path(self, res):
        return float(res)  # not a hot scope: silent
