# Compliant twin of fx_trace_bad: trace-stamped telemetry with
# catalogued fields only — the hedge resolution and request records as
# net/router.py and serve/records.py emit them (trace_id + the emitting
# hop's span_id + its parent), a batch event listing its member
# requests' traces, and a journal-style record carrying the wire-form
# header under ``trace`` (replays resume the ORIGINAL trace).


def hedge_record(logger, backend, primary, ctx):
    logger.event(
        {
            "event": "hedge",
            "backend": backend,
            "primary": primary,
            "delay_ms": 84.5,
            "outcome": "hedge_won",
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
        }
    )


def request_record(logger, rid, ctx):
    logger.event(
        {
            "event": "request",
            "id": rid,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_span_id": ctx.parent_span_id,
        }
    )


def batch_and_journal_records(logger, ctxs, header):
    logger.event(
        {
            "event": "batch",
            "bucket": "m256n512",
            "trace_ids": [c.trace_id for c in ctxs],
        }
    )
    logger.event(
        {
            "event": "journal_replay",
            "replayed": 1,
            "trace": header,
        }
    )
