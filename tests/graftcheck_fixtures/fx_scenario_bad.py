# Seeds: jit-nonhoisted x1 + dtype-explicit x2 + jsonl-fields x1 —
# scenario-engine idioms written wrong. Checked with
# pkg_path="backends/scenario_fx.py": the per-call jit around the Schur
# batch re-traces every factorize (the exact warm-recompile class the
# K-bucket ladder exists to prevent), the stacked-lane pad buffers must
# pin their dtype, and a scenario record field outside the catalogued
# schema (analysis/config.JSONL_FIELDS) is invisible to `cli report`.
import jax
import jax.numpy as jnp


def schur_chunk(W, dK):
    # a fresh jit per factorize call -> jit-nonhoisted
    return jax.jit(lambda w, d: jnp.einsum("kmn,kn,kpn->kmp", w, d, w))(
        W, dK
    )


def pad_lanes(k_pad, mb, nb):
    W = jnp.zeros((k_pad, mb, nb))  # dtype-explicit
    rowmask = jnp.ones((k_pad, mb))  # dtype-explicit
    return W, rowmask


def scenario_record(logger, n_scenarios, schur_ms):
    logger.event(
        {
            "event": "request",
            "n_scenarios": n_scenarios,
            "schur_ms": schur_ms,
            "scenario_lanes_used": 4,  # jsonl-fields: not catalogued
        }
    )
