# Seeds: dtype-narrow x2 — a df32-style pack/split written OUTSIDE the
# sanctioned two-float module. Checked with pkg_path="ipm/fx.py": the
# narrowing belongs in ops/df32.py (NARROW_SANCTIONED), anywhere else it
# is unbudgeted precision loss.
import jax.numpy as jnp

f32 = jnp.float32


def pack_pair(x):
    hi = x.astype(jnp.float32)  # dtype-narrow
    lo = (x - hi.astype(jnp.float64)).astype(f32)  # dtype-narrow
    return hi, lo
