# Seeds: dtype-explicit x2 + dtype-narrow x1 — sparse-ops idioms written
# OUTSIDE the sanctioned matrix-free modules. Checked with
# pkg_path="ipm/fx.py": the ELL pad buffers must pin their dtype (an
# unpinned jnp.zeros rides the x64 flag) and the f32 probe-factor
# narrowing belongs in ops/pcg.py (NARROW_SANCTIONED), anywhere else it
# is unbudgeted precision loss.
import jax.numpy as jnp


def ell_pad(m, k):
    vals = jnp.zeros((m, k))  # dtype-explicit
    cols = jnp.full((m, k), 0)  # dtype-explicit
    return vals, cols


def probe_factor(diag):
    return (1.0 / diag).astype(jnp.float32)  # dtype-narrow
