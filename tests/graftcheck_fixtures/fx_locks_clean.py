# Compliant twin of fx_locks_bad: direct lock, condition alias, and the
# caller-holds annotation all satisfy the rule; __init__ is exempt.
import threading


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._span_lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._results = []  # guarded-by: _lock
        self._spans = []  # guarded-by: _span_lock
        self._results.append("init")  # construction happens-before

    def direct(self, r):
        with self._lock:
            self._results.append(r)

    def via_condition(self):
        with self._wake:
            return len(self._results)

    def caller_holds(self):  # holds: _lock
        return list(self._results)

    def spans(self):
        with self._span_lock:
            return list(self._spans)
