# Seeds: jsonl-fields x2 — distributed-tracing telemetry written wrong.
# Checked with pkg_path="net/fx.py": a hedge resolution stamping its
# trace identity under keys the catalogue never heard of (the fleet
# aggregator's flow stitching keys on args.trace_id/trace_ids — these
# records would never connect), and a request record carrying an
# uncatalogued span-linkage field.


def hedge_record(logger, backend, primary, ctx):
    logger.event(
        {
            "event": "hedge",
            "backend": backend,
            "primary": primary,
            "outcome": "hedge_won",
            "traceparent": ctx.to_header(),  # jsonl-fields: not catalogued
        }
    )


def request_record(logger, rid, ctx):
    logger.event(
        {
            "event": "request",
            "id": rid,
            "trace_id": ctx.trace_id,
            "span_ref": ctx.span_id,  # jsonl-fields: not catalogued
        }
    )
