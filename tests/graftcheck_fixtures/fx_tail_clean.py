# Compliant twin of fx_tail_bad: the tail-tolerance event family with
# catalogued fields only — hedge resolutions and route legs as
# net/router.py emits them, cancellations as the router's loser-cancel
# and the backend's queue-removal emit them, retry-budget exhaustions
# and expired-on-arrival deadline rejections as net/router.py and
# net/server.py emit them.


def hedge_records(logger, backend, primary, tenant):
    logger.event(
        {
            "event": "hedge",
            "backend": backend,
            "primary": primary,
            "delay_ms": 84.5,
            "outcome": "hedge_won",
            "tenant": tenant,
        }
    )
    logger.event(
        {
            "event": "route",
            "backend": backend,
            "path": "/v1/solve",
            "code": 202,
            "ms": 12.25,
            "retried": False,
            "hedge": True,
        }
    )


def cancel_records(logger, backend, jid, tenant):
    logger.event(
        {
            "event": "cancel",
            "backend": backend,
            "jid": jid,
            "tenant": tenant,
            "code": 200,
            "state": "cancelled",
        }
    )
    logger.event(
        {
            "event": "cancel",
            "jid": jid,
            "id": 7,
            "name": "tail-7",
            "tenant": tenant,
            "state": "cancelled",
            "queue_ms": 18.75,
        }
    )


def budget_and_deadline_records(logger, tenant):
    logger.event(
        {
            "event": "retry_budget",
            "tenant": tenant,
            "kind": "hedge",
            "reason": "exhausted",
        }
    )
    logger.event(
        {
            "event": "deadline_expired",
            "path": "/v1/solve",
            "tenant": tenant,
            "remaining_ms": 0.0,
        }
    )
