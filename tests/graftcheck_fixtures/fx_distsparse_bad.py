"""Seeded distributed-sparse-tier violations (graftcheck twin test,
pkg_path backends/fx.py). The row-sharded matrix-free idioms written
WRONG: an ELL row-block pad buffer riding the x64 flag (dtype-explicit
x2), an f32 preconditioner-factor narrowing outside the sanctioned
modules (dtype-narrow), and a default-device rhs entering the
mesh-programmed PCG (spmd-uncommitted-input) — the exact bug class
that works on one device and silently misplaces on a pod."""

import jax.numpy as jnp


def shard_pad_buffers(r, mb_pad, k):
    vals = jnp.zeros((r, mb_pad, k))  # dtype-explicit
    cols = jnp.full((r, mb_pad, k), 0)  # dtype-explicit
    return vals, cols


def shard_local_factor(diag):
    return (1.0 / diag).astype(jnp.float32)  # dtype-narrow


def solve_sharded(mv, prec, b, mesh):
    # spmd-uncommitted-input: jnp.asarray commits to the default device;
    # the mesh-programmed pcg then reshuffles every iteration (or
    # deadlocks a multi-process world).
    rhs = jnp.asarray(b)
    return pcg(mv, prec, rhs, 1e-8, 200, mesh=mesh)


def pcg(mv, prec, rhs, tol, max_iter, mesh=None):
    return rhs
