"""Seeded static-deadlock violations (graftcheck twin test, pkg_path
serve/fx.py): a cross-method lock-order cycle the dynamic recorder
would only catch if a run happened to interleave it, and a blocking
HTTP round-trip held under a lock."""

import threading
import urllib.request


class Pipeline:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def pack(self):
        # a -> b, through a call: the edge the lexical checker of PR 6
        # could not see.
        with self._a:
            self._note()

    def _note(self):
        with self._b:
            pass

    def solve(self):
        # b -> a: closes the cycle with pack()'s a -> b.
        with self._b:
            with self._a:
                pass

    def push(self, payload):
        # blocking-under-lock: an HTTP round-trip while holding _a.
        with self._a:
            urllib.request.urlopen("http://example/submit", payload)
