# Compliant twin of fx_elastic_bad: the closed-loop elasticity event
# family with catalogued fields only — scale actions and vetoes as
# serve/elastic.py emits them, brownout-ladder transitions as
# net/admission.py emits them, breaker trips as net/router.py emits
# them.


def scale_records(logger, url, pool, target):
    logger.event(
        {
            "event": "scale_out",
            "reason": "queue_depth",
            "backend": url,
            "pool": pool,
            "target": target,
            "ms": 1830.0,
            "pid": 4242,
        }
    )
    logger.event(
        {
            "event": "scale_in",
            "reason": "load_low",
            "backend": url,
            "pool": pool,
            "target": target,
            "ms": 210.0,
            "drained": True,
        }
    )
    logger.event(
        {
            "event": "scale_veto",
            "reason": "cooldown",
            "pool": pool,
            "target": target,
            "detail": "signal=queue_depth",
        }
    )


def brownout_records(logger, depth):
    logger.event(
        {
            "event": "brownout_enter",
            "stage": 1,
            "reason": "queue_depth",
            "queue_depth": depth,
        }
    )
    logger.event(
        {
            "event": "brownout_exit",
            "stage": 0,
            "reason": "calm",
            "queue_depth": depth,
            "ms": 2400.0,
        }
    )


def breaker_records(logger, backend):
    logger.event(
        {
            "event": "breaker_open",
            "backend": backend,
            "reason": "error_rate",
            "error_rate": 0.62,
            "backoff_s": 2.0,
        }
    )
    logger.event(
        {
            "event": "breaker_close",
            "backend": backend,
            "reason": "half_open_trial_ok",
        }
    )
