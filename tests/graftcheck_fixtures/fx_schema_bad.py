# Seeds: jsonl-fields x2 (stray field, unknown event type) and
# jsonl-stamp (record written without stamp_record).
import json


def emit(logger, out, rec):
    logger.event(
        {
            "event": "request",
            "id": 1,
            "bogus_field": True,  # jsonl-fields: not catalogued
        }
    )
    logger.event({"event": "totally_new_event"})  # jsonl-fields: type
    out.write(json.dumps(rec) + "\n")  # jsonl-stamp: unstamped
