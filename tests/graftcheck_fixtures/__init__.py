# Seeded-violation fixtures for the graftcheck analyzer tests. These
# modules are parsed by the checker, never imported — each fx_*_bad.py
# seeds exactly the violations its test expects, and each fx_*_clean.py
# is the compliant twin that must stay silent.
