"""Seeded spmd-* violations (graftcheck twin test, pkg_path
distributed/fx.py). Every def here breaks the multi-host SPMD contract
one way: a rank-gated collective, an early rank exit skipping one, a
rank fact passed into a param-sensitive callee, a rank-filtered
comprehension wrapping one, unordered iteration feeding world-visible
publication, and an uncommitted array entering a mesh program."""

import os

import jax.numpy as jnp


def rank_gated_report(world, stats):
    # spmd-divergent-collective: only rank 0 reaches the barrier; every
    # follower hangs in it forever.
    if world.rank == 0:
        world.barrier("report")
    return stats


def early_exit_skips_collective(world, value):
    # spmd-divergent-collective: nonzero ranks leave before the
    # allgather the primary then blocks in.
    primary = world.rank == 0
    if not primary:
        return None
    return world.allgather(value)


def _publish_if(primary, world):
    if primary:
        world.barrier("pub")


def caller(world):
    # spmd-divergent-collective (call-argument taint): the divergence
    # lives one call down, seeded here.
    _publish_if(world.rank == 0, world)


def gather_primary_only(world, shards):
    # spmd-divergent-collective (comprehension filter): the rank test
    # hides in the generator's `if`, so only rank 0 ever enters the
    # allgather — followers hang, and a statement-level If/While walk
    # never sees the guard.
    return [world.allgather(s) for s in shards if world.rank == 0]


def replay_dispatches(control, journal_dir):
    # spmd-unordered-dispatch: filesystem order feeds the dispatch
    # journal — ranks replay in different orders.
    for fname in os.listdir(journal_dir):
        control.publish({"f": fname})


def warm_world(service, shapes):
    # spmd-unordered-dispatch: set order differs per process hash seed,
    # so the warm-up publication order diverges across the world.
    pending = set(shapes)
    for spec in pending:
        service.publish(spec)


def dispatch_bucket(batch, active, cfg, mesh):
    # spmd-uncommitted-input: a bare default-device commit entering the
    # mesh program.
    act = jnp.asarray(active)
    return solve_bucket(batch, act, cfg, mesh=mesh)


def solve_bucket(batch, active, cfg, mesh=None):
    return batch
