# Compliant twin of fx_sparse_bad: the IDENTICAL idioms are clean when
# the pad buffers pin their dtypes and the probe-factor narrowing lives
# in the sanctioned matrix-free module — checked with
# pkg_path="ops/pcg.py" (analysis/config.NARROW_SANCTIONED; ops/sparse.py
# is sanctioned the same way). dtype-explicit applies everywhere in
# ops/, so the constructors still pin.
import jax.numpy as jnp


def ell_pad(m, k):
    vals = jnp.zeros((m, k), jnp.float64)
    cols = jnp.zeros((m, k), jnp.int32)
    return vals, cols


def probe_factor(diag):
    return (1.0 / diag).astype(jnp.float32)  # sanctioned: loose-solve factor
