# Compliant twin of fx_jit_bad: module-level wrappers, statics declared,
# donate_argnums present on the catalogued program.
import functools

import jax
import jax.numpy as jnp


@functools.partial(
    jax.jit, static_argnames=("params", "scale"), donate_argnums=(1,)
)
def _batched_segment_jit(A, carry, params, scale=2.0):
    return carry * scale


@jax.jit
def _sum_sq(x):
    return (x * x).sum()


def per_call_wrapper(v):
    return _sum_sq(v)
