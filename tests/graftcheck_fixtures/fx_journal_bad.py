# Seeds for the crash-safe-fabric schema additions: jsonl-fields x2 (a
# journal_replay payload carrying an uncatalogued tally, a misspelled
# drain event type) and jsonl-stamp (a WAL record written without
# stamp_record — the replay loader depends on the ts stamp for
# deadline accounting).
import json


def emit(logger, wal, rec):
    logger.event(
        {
            "event": "journal_replay",
            "replayed": 3,
            "resurrected": 1,  # jsonl-fields: not catalogued
        }
    )
    logger.event({"event": "drain_started"})  # jsonl-fields: type
    wal.write(json.dumps(rec) + "\n")  # jsonl-stamp: unstamped
