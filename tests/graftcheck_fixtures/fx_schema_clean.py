# Compliant twin of fx_schema_bad: catalogued fields/types only, and the
# out-stream write routes through stamp_record.
import json

from distributedlpsolver_tpu.utils.logging import stamp_record


def emit(logger, out, rec):
    logger.event(
        {
            "event": "request",
            "id": 1,
            "status": "optimal",
            "queue_ms": 0.5,
        }
    )
    out.write(json.dumps(stamp_record(rec)) + "\n")
