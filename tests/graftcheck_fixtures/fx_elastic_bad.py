# Seeds: jsonl-schema x2 — elasticity telemetry written wrong. Checked
# with pkg_path="serve/fx.py": a scale action under a type the event
# catalogue never heard of (invisible to `cli report` and the bench's
# pool-trajectory reconstruction), and a breaker trip carrying an
# uncatalogued rate field.


def scale_record(logger, pool, target):
    logger.event(
        {
            "event": "pool_resize",  # jsonl-event-type: not catalogued
            "pool": pool,
            "target": target,
        }
    )


def breaker_record(logger, backend, rate):
    logger.event(
        {
            "event": "breaker_open",
            "backend": backend,
            "trip_rate": rate,  # jsonl-fields: not catalogued
        }
    )
