"""Clean twin of fx_distsparse_bad.py (pkg_path backends/fx.py): the
same row-sharded matrix-free idioms written to contract — pinned pad
dtypes, f64 factors, rhs committed via put_global against the mesh (the
single-device fallback keeps its bare asarray under the mesh-None
guard), and the operator itself entering the sink through shard_rows
(a committed placer)."""

import jax.numpy as jnp


def shard_pad_buffers(r, mb_pad, k, dtype):
    vals = jnp.zeros((r, mb_pad, k), dtype=dtype)
    cols = jnp.full((r, mb_pad, k), 0, dtype=jnp.int32)
    return vals, cols


def shard_local_factor(diag):
    return 1.0 / diag  # stays in the operator dtype


def solve_sharded(A, mv, prec, b, mesh):
    op = shard_rows(A, mesh)
    if mesh is None:
        rhs = jnp.asarray(b)
    else:
        rhs = put_global(b, batch_sharding(mesh, 1))
    return pcg(mv, prec, op.embed(rhs), 1e-8, 200, mesh=mesh)


def pcg(mv, prec, rhs, tol, max_iter, mesh=None):
    return rhs


def shard_rows(A, mesh):
    return A


def put_global(x, sharding):
    return x


def batch_sharding(mesh, ndim):
    return None
