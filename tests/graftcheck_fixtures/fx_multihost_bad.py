# Seeds: jsonl-fields x2 + guarded-by x1 — multi-host runtime idioms
# written wrong. Checked with pkg_path="distributed/fx.py": a
# world_reinit record carrying an uncatalogued tally (invisible to
# `cli report`'s recovery summary), a heartbeat event misspelling the
# rank field, and the slice runner's dispatch counter read without the
# lock its guarded-by annotation names (the publish-order invariant the
# lock exists for).
import threading


class SliceState:
    def __init__(self):
        self._lock = threading.Lock()
        self.dispatches = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.dispatches += 1

    def snapshot(self):
        return self.dispatches  # guarded-by violation: read unlocked


def reinit_record(logger, generation, overhead_s):
    logger.event(
        {
            "event": "world_reinit",
            "generation": generation,
            "recovery_overhead_s": overhead_s,
            "ranks_lost_count": 1,  # jsonl-fields: not catalogued
        }
    )


def heartbeat_record(logger, rank):
    logger.event(
        {
            "event": "heartbeat",
            "beat_rank": rank,  # jsonl-fields: not catalogued ("rank")
        }
    )
