"""Property tests for the double-f32 arithmetic layer (ops/df32.py):
error bounds vs f64 across magnitude ranges, renormalization invariants,
NaN/inf propagation, and the f64-in/out KKT chain helpers the IPM core
routes through under StepParams.elementwise == "df32"."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distributedlpsolver_tpu.ops import df32  # noqa: E402

# Per-op bounds are ~15u² ≈ 5.3e-14 (module docstring); chains compound a
# handful of ops plus the 2⁻⁴⁹ pack error. 1e-12 relative leaves ~20×
# slack without ever passing a plain-f32 (1e-7) regression.
_REL = 1e-12
# Magnitude decades well inside the documented df32 validity range:
# |x| ≲ 4e34 (Dekker split) and |results| ≳ 4e-31 (low limb above the
# f32 subnormal floor) — products at the extreme scales stay legal.
_SCALES = (1e-12, 1e-6, 1.0, 1e6, 1e12)


def _rel_err(got, ref):
    got, ref = np.asarray(got), np.asarray(ref)
    return np.max(np.abs(got - ref) / (np.abs(ref) + 1e-300))


def _rand(rng, n, scale):
    # Bounded away from zero so relative error is meaningful and sums
    # are well-conditioned (cancellation amplifies the *input* rounding
    # of any finite representation — that is conditioning, not an
    # arithmetic defect, so it is excluded here by construction).
    return scale * (rng.uniform(0.1, 10.0, n) * rng.choice([-1.0, 1.0], n))


class TestErrorBounds:
    @pytest.mark.parametrize("scale", _SCALES)
    def test_add_sub_mul_div_vs_f64(self, scale):
        rng = np.random.default_rng(7)
        x = jnp.asarray(_rand(rng, 2048, scale))
        y = jnp.asarray(_rand(rng, 2048, scale))
        X, Y = df32.pack(x), df32.pack(y)
        assert _rel_err(df32.unpack(df32.mul(X, Y)), x * y) < _REL
        assert _rel_err(df32.unpack(df32.div(X, Y)), x / y) < _REL
        # Same-sign addition is perfectly conditioned — the clean probe
        # of the additive bound.
        xs, ys = jnp.abs(x), jnp.abs(y)
        XS, YS = df32.pack(xs), df32.pack(ys)
        assert _rel_err(df32.unpack(df32.add(XS, YS)), xs + ys) < _REL
        assert _rel_err(df32.unpack(df32.sub(XS, df32.neg(YS))), xs + ys) < _REL

    def test_pack_roundtrip_exact_to_2e49(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(_rand(rng, 4096, 1.0))
        assert _rel_err(df32.unpack(df32.pack(x)), x) < 2.0**-48

    def test_cross_magnitude_products(self):
        # Mixed scales inside one op: hi/lo split must track the large
        # component while preserving the small one's digits.
        rng = np.random.default_rng(11)
        x = jnp.asarray(_rand(rng, 1024, 1e12))
        y = jnp.asarray(_rand(rng, 1024, 1e-12))
        assert _rel_err(df32.mul64(x, y), x * y) < _REL

    def test_under_jit(self):
        rng = np.random.default_rng(5)
        x = jnp.asarray(_rand(rng, 512, 1.0))
        y = jnp.asarray(_rand(rng, 512, 1.0))
        f = jax.jit(
            lambda a, b: df32.unpack(df32.div(df32.pack(a), df32.pack(b)))
        )
        assert _rel_err(f(x, y), x / y) < _REL


class TestRenormalization:
    def test_pair_invariant_after_ops(self):
        # |lo| ≤ ulp(hi)/2 ⇒ |lo| ≤ 2⁻²³·|hi| — the renormalized-pair
        # invariant every op re-establishes via fast_two_sum.
        rng = np.random.default_rng(9)
        x = jnp.asarray(_rand(rng, 1024, 1.0))
        y = jnp.asarray(_rand(rng, 1024, 1.0))
        X, Y = df32.pack(x), df32.pack(y)
        for hi, lo in (
            df32.pack(x),
            df32.add(X, Y),
            df32.mul(X, Y),
            df32.div(X, Y),
            df32.renorm(Y[0], Y[1]),
        ):
            hi, lo = np.asarray(hi), np.asarray(lo)
            assert np.all(np.abs(lo) <= 2.0**-23 * np.abs(hi) + 1e-45)

    def test_two_sum_exact(self):
        # The error-free transformation really is error-free: s + e
        # reconstructs the f64 sum of the f32 inputs exactly.
        rng = np.random.default_rng(2)
        a = jnp.asarray(rng.standard_normal(1024), jnp.float32)
        b = jnp.asarray(rng.standard_normal(1024) * 1e-4, jnp.float32)
        s, e = df32.two_sum(a, b)
        exact = a.astype(jnp.float64) + b.astype(jnp.float64)
        got = s.astype(jnp.float64) + e.astype(jnp.float64)
        assert np.array_equal(np.asarray(got), np.asarray(exact))

    def test_two_prod_exact(self):
        rng = np.random.default_rng(4)
        a = jnp.asarray(rng.standard_normal(1024), jnp.float32)
        b = jnp.asarray(rng.standard_normal(1024), jnp.float32)
        p, e = df32.two_prod(a, b)
        exact = a.astype(jnp.float64) * b.astype(jnp.float64)
        got = p.astype(jnp.float64) + e.astype(jnp.float64)
        assert np.array_equal(np.asarray(got), np.asarray(exact))


class TestNonFinite:
    def test_nan_propagates(self):
        x = jnp.asarray([np.nan, 1.0, np.nan])
        y = jnp.asarray([1.0, np.nan, 2.0])
        for op in (df32.add, df32.sub, df32.mul, df32.div):
            out = np.asarray(df32.unpack(op(df32.pack(x), df32.pack(y))))
            assert not np.isfinite(out[0]) and not np.isfinite(out[1])

    def test_inf_yields_nonfinite(self):
        # inf arithmetic produces inf−inf = NaN inside the EFTs; the
        # contract is only "non-finite in → non-finite out" (the solver's
        # bad-step detection tests finiteness, nothing else).
        x = jnp.asarray([np.inf, -np.inf, 1.0])
        y = jnp.asarray([1.0, 2.0, np.inf])
        for op in (df32.add, df32.mul, df32.div):
            out = np.asarray(df32.unpack(op(df32.pack(x), df32.pack(y))))
            assert not np.any(np.isfinite(out))

    def test_finite_lanes_unpolluted(self):
        # Elementwise: a non-finite lane never contaminates its
        # neighbours (the masking design of the batched loop depends on
        # per-member independence).
        x = jnp.asarray([np.nan, 3.0])
        y = jnp.asarray([1.0, 2.0])
        out = np.asarray(df32.unpack(df32.mul(df32.pack(x), df32.pack(y))))
        assert not np.isfinite(out[0]) and abs(out[1] - 6.0) < 1e-12


class TestKKTChains:
    """The f64-in/out chain helpers ipm/core.py calls under
    elementwise="df32" match their native-f64 formulas to chain-level
    bounds (≲1e-13; asserted at 1e-11 across adversarial IPM-like
    spreads)."""

    _CHAIN_REL = 1e-11

    def _iterate(self, n=1536, seed=0):
        # Late-IPM-like spreads: x/s spanning ~12 orders against w/z a
        # few orders — the conditioning the scaling chain actually sees.
        rng = np.random.default_rng(seed)
        x = jnp.asarray(10.0 ** rng.uniform(-9, 3, n))
        s = jnp.asarray(10.0 ** rng.uniform(-9, 3, n))
        w = jnp.asarray(10.0 ** rng.uniform(-4, 2, n))
        z = jnp.asarray(10.0 ** rng.uniform(-4, 2, n))
        hub = jnp.asarray((rng.random(n) > 0.4).astype(np.float64))
        return x, s, w, z, hub, rng

    def test_scaling_d(self):
        x, s, w, z, hub, _ = self._iterate()
        ref = 1.0 / (s / x + hub * z / w + 1e-8)
        got = df32.scaling_d(x, s, w, z, hub, 1e-8)
        assert _rel_err(got, ref) < self._CHAIN_REL

    def test_kkt_back_substitution(self):
        x, s, w, z, hub, rng = self._iterate(seed=1)
        n = x.shape[0]
        r_d = jnp.asarray(_rand(rng, n, 1.0))
        r_xs = jnp.asarray(_rand(rng, n, 1e-3))
        r_wz = hub * jnp.asarray(_rand(rng, n, 1e-3))
        r_u = hub * jnp.asarray(_rand(rng, n, 1e-2))
        d = jnp.asarray(10.0 ** rng.uniform(-8, 8, n))
        aty = jnp.asarray(_rand(rng, n, 1.0))

        h_ref = r_d - r_xs / x + (r_wz - z * r_u) / w
        h = df32.kkt_h(r_d, r_xs, x, r_wz, z, r_u, w)
        assert _rel_err(h, h_ref) < self._CHAIN_REL

        dx_ref = d * (aty - h_ref)
        dx = df32.kkt_dx(d, aty, h)
        assert _rel_err(dx, dx_ref) < self._CHAIN_REL

        ds_ref = (r_xs - s * dx_ref) / x
        assert _rel_err(df32.kkt_ds(r_xs, s, dx, x), ds_ref) < self._CHAIN_REL

        dw_ref = r_u - dx_ref
        dw = df32.sub64(r_u, dx)
        # dw is a difference of near-equal magnitudes in places; compare
        # against the direction scale, not the (possibly cancelled) dw.
        scale = np.max(np.abs(np.asarray(dx_ref))) + 1.0
        assert np.max(np.abs(np.asarray(dw - dw_ref))) < self._CHAIN_REL * scale

        dz_ref = hub * (r_wz - z * dw_ref) / w
        dz = df32.kkt_dz(hub, r_wz, z, dw, w)
        err = np.max(np.abs(np.asarray(dz - dz_ref)))
        assert err < 1e-9 * (np.max(np.abs(np.asarray(dz_ref))) + 1.0)

    def test_step_params_routes_df32(self):
        # The core seam: a StepParams with elementwise="df32" makes
        # scaling_d numerically track the df32 chain, not native f64.
        from distributedlpsolver_tpu.ipm import core
        from distributedlpsolver_tpu.ipm.config import SolverConfig
        from distributedlpsolver_tpu.ipm.state import IPMState

        x, s, w, z, hub, _ = self._iterate(n=256, seed=2)
        state = IPMState(x=x, y=jnp.zeros(4), s=s, w=w, z=z)
        data = core.make_problem_data(
            jnp, jnp.ones_like(x), jnp.ones(4),
            jnp.where(hub > 0, 2.0 * x, jnp.inf), jnp.float64,
        )
        cfg = SolverConfig()
        d_native = core.scaling_d(state, data, cfg.step_params())
        d_df32 = core.scaling_d(
            state, data, cfg.step_params(elementwise="df32")
        )
        expect = df32.scaling_d(x, s, w, z, data.hub, cfg.reg_primal)
        assert np.array_equal(np.asarray(d_df32), np.asarray(expect))
        assert _rel_err(d_df32, d_native) < self._CHAIN_REL
