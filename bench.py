"""Benchmark harness — prints ONE JSON line for the round driver.

Metric (BASELINE.json:2): IPM iterations/sec and wall-clock to a 1e-8
relative duality gap. The reference publishes no numbers and no pds-20
file is fetchable in this zero-egress image (BASELINE.md), so the
headline config is the block-angular generator at a pds-like shape, and
``vs_baseline`` compares the accelerated backend against the same
problem solved by this package's own host/CPU path on this machine —
the stand-in for the reference's 8-rank MPI/CPU baseline until real
Netlib files are present in ``data/`` (drop pds-20.mps there to switch
the bench to it automatically).

Usage: python bench.py [--quick] [--backend tpu|sharded] [--json-only]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _solve_timed(problem, backend: str, **cfg):
    from distributedlpsolver_tpu.ipm import solve

    r = solve(problem, backend=backend, **cfg)
    return r


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes (smoke)")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--baseline-backend", default="cpu-native")
    ap.add_argument("--mps", default=None, help="bench this MPS file instead")
    args = ap.parse_args()

    import jax

    try:
        devs = jax.devices()
    except RuntimeError as e:  # accelerator claim failed — fall back to CPU
        _log(f"accelerator unavailable ({e}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
    _log(f"devices: {devs}")

    from distributedlpsolver_tpu.backends import available_backends
    from distributedlpsolver_tpu.models.generators import block_angular_lp
    from distributedlpsolver_tpu.io.mps import read_mps

    pds20_path = args.mps or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "data", "pds-20.mps"
    )
    if os.path.exists(pds20_path):
        problem = read_mps(pds20_path)
        config_name = os.path.basename(pds20_path)
    elif args.quick:
        problem = block_angular_lp(4, 24, 48, 12, seed=0, sparse=False)
        config_name = "block_angular(K=4,24x48,link=12) [quick]"
    else:
        # pds-like block-angular stand-in (BASELINE.json:8 structure).
        problem = block_angular_lp(8, 96, 256, 64, seed=0, sparse=False)
        config_name = "block_angular(K=8,96x256,link=64) pds-like stand-in"

    backend = args.backend
    if backend not in available_backends():
        _log(f"backend {backend!r} unknown; using 'tpu'")
        backend = "tpu"

    # Warm-up solve (compile) then timed solve.
    _log(f"warm-up (compile) on backend={backend} ...")
    _solve_timed(problem, backend, max_iter=3)
    _log("timed solve ...")
    r = _solve_timed(problem, backend)
    _log(r.summary())

    # Baseline: same problem on the host/CPU reference path.
    vs_baseline = None
    base = args.baseline_backend
    if base not in available_backends():
        base = None
    if base and base != backend:
        try:
            _solve_timed(problem, base, max_iter=3)
            rb = _solve_timed(problem, base)
            _log("baseline " + rb.summary())
            if rb.solve_time > 0 and r.solve_time > 0:
                vs_baseline = rb.solve_time / r.solve_time
        except Exception as e:  # baseline must never sink the bench
            _log(f"baseline failed: {e}")
    if vs_baseline is None:
        vs_baseline = 1.0

    print(
        json.dumps(
            {
                "metric": (
                    "wall-clock to 1e-8 rel duality gap, "
                    f"{config_name}, backend={backend} "
                    f"[{r.iterations} iters, {r.iters_per_sec:.2f} it/s, "
                    f"status={r.status.value}]"
                ),
                "value": round(r.solve_time, 4),
                "unit": "seconds",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
