"""Benchmark harness — prints ONE JSON line for the round driver.

Metric (BASELINE.json:2): IPM iterations/sec and wall-clock to a 1e-8
relative duality gap. The reference publishes no numbers and no Netlib/
Mittelmann files are fetchable in this zero-egress image (BASELINE.md), so
each of the reference's five benchmark configs (BASELINE.json:7-11) runs on
a generated stand-in of the same structure and scale class, and
``vs_baseline`` compares the accelerated backend against this package's own
host/CPU path on the same problem — the stand-in for the reference's 8-rank
MPI/CPU baseline until real files are present in ``data/`` (drop
``pds-20.mps`` there to switch the headline bench to it automatically).

Usage:
  python bench.py [--quick] [--backend tpu|sharded] [--mps FILE]
  python bench.py --suite [--quick]    # all five reference configs,
                                       # detailed rows → BENCH_SUITE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.abspath(__file__))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _obs_enable() -> None:
    """Install a process-wide metrics registry for the whole bench run,
    so every row can embed the snapshot (the BENCH_*.json trajectories
    become self-describing: a row says how many programs compiled and
    what the padding waste was, not just how fast it went)."""
    from distributedlpsolver_tpu.obs import metrics as obs_metrics

    obs_metrics.set_registry(obs_metrics.MetricsRegistry())


def _obs_row(platform: str) -> dict:
    """Compact metrics snapshot stamped into each suite row: platform,
    cumulative compile/iteration counters, and the serve-path padding
    waste + pack/solve overlap ratio (None until a serve row ran)."""
    from distributedlpsolver_tpu.obs import metrics as obs_metrics

    snap = obs_metrics.get_registry().snapshot()

    def _hist(name):
        h = snap.get(name)
        return h if isinstance(h, dict) and h.get("count") else None

    waste = _hist("serve_padding_waste")
    overlap, solve = _hist("serve_overlap_ms"), _hist("serve_solve_ms")
    return {
        "platform": platform,
        "ipm_iterations_total": int(snap.get("ipm_iterations_total", 0)),
        "bucket_programs_compiled": int(
            snap.get("bucket_programs_compiled_total", 0)
        ),
        "serve_bucket_compiles": int(
            snap.get("serve_bucket_compiles_total", 0)
        ),
        "serve_padding_waste_mean": (
            round(waste["sum"] / waste["count"], 4) if waste else None
        ),
        "serve_overlap_ratio": (
            round(overlap["sum"] / solve["sum"], 4)
            if overlap and solve and solve["sum"] > 0 else None
        ),
    }


def _solve_timed(problem, backend: str, _retries: int = 2, **cfg):
    """solve() with retry on transient tunnel/runtime failures.

    The tunneled accelerator occasionally drops a request
    ("remote_compile: response body closed", worker restarts); the
    persistent XLA compile cache makes a retry cheap, so long benches
    should never sink on one transient (VERDICT.md round 1, item 9).
    """
    from distributedlpsolver_tpu.ipm import solve

    last = None
    for attempt in range(_retries + 1):
        try:
            return solve(problem, backend=backend, **cfg)
        except Exception as e:  # jax runtime errors don't share one base
            if not _is_transient(e) or attempt == _retries:
                raise
            last = e
            _log(
                f"  transient failure (attempt {attempt + 1}): {str(e)[:200]}"
            )
            time.sleep(5.0)
    raise last  # unreachable


def _is_transient(e: Exception) -> bool:
    """Tunnel/worker failure classification shared by every retry site.

    Specific tunnel-failure phrases retry regardless of type; the broad
    gRPC status tokens (UNAVAILABLE / DEADLINE_EXCEEDED) only count when
    they come from an XLA/PJRT runtime error — substring-matching them
    against arbitrary exception text would silently retry deterministic
    bugs whose wrapped message happens to contain one.
    """
    msg = str(e)
    return any(
        s in msg
        for s in (
            "remote_compile", "response body closed", "crashed or restarted",
        )
    ) or (
        type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError")
        and any(s in msg for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED"))
    )


def _headline_problem(args):
    """The headline config: a real pds-20.mps if present, else the pds-like
    block-angular stand-in (BASELINE.json:8 structure)."""
    from distributedlpsolver_tpu.io.mps import read_mps
    from distributedlpsolver_tpu.models.generators import block_angular_lp

    pds20_path = args.mps or os.path.join(_REPO, "data", "pds-20.mps")
    if os.path.exists(pds20_path):
        return read_mps(pds20_path), os.path.basename(pds20_path)
    if args.quick:
        return (
            block_angular_lp(4, 24, 48, 12, seed=0, sparse=False),
            "block_angular(K=4,24x48,link=12) [quick]",
        )
    return (
        block_angular_lp(8, 96, 256, 64, seed=0, sparse=False),
        "block_angular(K=8,96x256,link=64) pds-like stand-in",
    )


def _bench_one(problem, backend: str, baseline: str | None, **cfg):
    """Warm-up (compile) + timed solve on ``backend``; optional baseline
    solve for the speedup ratio. Returns a result row dict."""
    from distributedlpsolver_tpu.backends import available_backends

    # Warm-up at the SAME config as the timed solve: segmented backends
    # key their compiled programs on buffer_cap(n_phases·max_iter), so a
    # small-max_iter warm-up compiles a never-reused bucket and the timed
    # solve pays the real compile (observed: storm-class row 74 s cold vs
    # 10 s warm). A full warm solve costs seconds; a cold compile in the
    # timed region costs the row its meaning. The timed figure is the
    # best of two: the tunneled worker shows occasional one-off ~8×
    # slowness on a fully warm program (observed on the storm row
    # mid-suite, unreproducible in isolation) and a single sample can't
    # tell that from a regression.
    _solve_timed(problem, backend, **cfg)
    r = _solve_timed(problem, backend, **cfg)
    r2 = _solve_timed(problem, backend, **cfg)
    if r2.solve_time < r.solve_time:
        r = r2
    _log(f"  {backend}: " + r.summary())
    row = {
        "backend": getattr(r, "backend", backend),
        "time_s": round(r.solve_time, 4),
        "iters": int(r.iterations),
        "iters_per_sec": round(r.iters_per_sec, 2),
        "status": r.status.value,
        # Every row records the tolerance it was solved to — rows at a
        # looser tol (e.g. first-order configs) must say so (VERDICT.md).
        "tol": cfg.get("tol", 1e-8),
        # null until a baseline is actually measured (same rule as the
        # batched row): a fabricated neutral 1.0 reads as "measured, no
        # speedup" — e.g. the dense 2048x10240 row, whose CPU baseline
        # is deliberately not run at full size.
        "vs_baseline": None,
    }
    if baseline and baseline in available_backends() and baseline != backend:
        try:
            # Baselines are CPU paths (no segmented buffer_cap buckets to
            # warm) — a tiny warm-up covers any lazy init. The baseline is
            # best-of-two like the backend figure: filtering noise from
            # only one side of the ratio would bias vs_baseline upward.
            _solve_timed(problem, baseline, max_iter=3)
            rb = _solve_timed(problem, baseline)
            rb2 = _solve_timed(problem, baseline)
            if rb2.solve_time < rb.solve_time:
                rb = rb2
            _log(f"  baseline {baseline}: " + rb.summary())
            if rb.solve_time > 0 and r.solve_time > 0:
                row["baseline_backend"] = baseline
                row["baseline_time_s"] = round(rb.solve_time, 4)
                row["vs_baseline"] = round(rb.solve_time / r.solve_time, 3)
        except Exception as e:  # baseline must never sink the bench
            _log(f"  baseline {baseline} failed: {e}")
    return row


def _bench_batched(quick: bool):
    """Config 5 (BASELINE.json:11): 1024 independent (128, 512) LPs.

    The baseline is the reference's natural shape for this config — one
    LP at a time through the host/CPU path ("one LP per rank", looped).
    Solving all 1024 serially would dominate the bench budget, so a
    random subsample is measured and extrapolated (the problems are
    i.i.d. draws from one generator, so the mean is unbiased); the row
    records the sample size and per-problem mean alongside the estimate.
    """
    from distributedlpsolver_tpu.backends.batched import solve_batched
    from distributedlpsolver_tpu.models.generators import random_batched_lp

    B, m, n = (32, 16, 40) if quick else (1024, 128, 512)
    batch = random_batched_lp(B, m, n, seed=0)

    def batched_retry(**kw):
        # solve_batched with the same transient-retry the scalar rows get
        # (a TPU worker restart mid-batch sank a whole suite run once).
        # Returns (result, attempts): a retried TIMED solve pays the lost
        # worker's recompiles inside its own clock, so the caller re-runs
        # once warm rather than recording a compile-contaminated figure.
        for attempt in range(3):
            try:
                return solve_batched(batch, **kw), attempt + 1
            except Exception as e:
                if not _is_transient(e) or attempt == 2:
                    raise
                _log(f"  batched transient (attempt {attempt + 1}): "
                     f"{str(e)[:200]}")
                time.sleep(5.0)

    batched_retry(max_iter=3)  # compile warm-up (full-size programs)
    # One full untimed solve: final-phase compaction runs half-size
    # programs (256→128→64→32) whose compiles only happen once actives
    # drain — a max_iter=3 warm-up never reaches them, and ~100 s of
    # one-time compile inside the first timed figure would make
    # best-of-two load-bearing instead of a noise guard.
    batched_retry()
    try:
        # Warm the solo-cleanup path too: tail-extracted stragglers
        # re-solve through the dense backend, and its first compile
        # (~60 s observed for the two-phase segment programs at the
        # member shape) otherwise lands inside the timed solve. The
        # warm-up max_iter must land in the SAME buffer_cap bucket as a
        # real cleanup solve (buffer caps are static jit keys), so both
        # the figure and the backend name come from batched's own
        # cleanup logic — a hardcoded pair silently compiles a
        # never-reused executable whenever the defaults move. The solve
        # itself converges in ~20 iterations, so the large bound only
        # shapes the bucket, not the runtime.
        from distributedlpsolver_tpu.backends.batched import (
            CLEANUP_BACKEND,
            cleanup_solo_max_iter,
            member_interior_form,
        )
        from distributedlpsolver_tpu.ipm.driver import solve as _solo_solve

        _solo_solve(member_interior_form(batch, 0), backend=CLEANUP_BACKEND,
                    max_iter=cleanup_solo_max_iter(member_entries=m * n))
    except Exception as e:
        _log(f"  solo-path warm-up failed (non-fatal): {e}")
    # Re-time (bounded) until a run completes without a worker restart —
    # a retried run's clock includes the lost worker's recompiles.
    for retime in range(3):
        t0 = time.perf_counter()
        res, attempts = batched_retry()
        dt = time.perf_counter() - t0
        if attempts == 1:
            break
        if retime < 2:
            _log("  batched timed solve hit a worker restart; re-timing warm")
    timing_note = (
        "worker restarts on every timed attempt; figure includes recompiles"
        if attempts > 1 else None
    )
    ok = sum(1 for s in res.status if s.value == "optimal")
    _log(f"  batched: {B} LPs in {res.solve_time:.3f}s, {ok}/{B} optimal")
    # Per-member status breakdown (VERDICT round 3 item 2: the artifact
    # must say WHAT the non-optimal members are, not just how many).
    breakdown: dict = {}
    for s in res.status:
        breakdown[s.value] = breakdown.get(s.value, 0) + 1
    non_opt = [
        {"i": int(i), "status": res.status[i].value,
         "rel_gap": float(res.rel_gap[i]), "pinf": float(res.pinf[i])}
        for i in range(B) if res.status[i].value != "optimal"
    ]
    row = {
        "backend": "batched(vmap)",
        "time_s": round(res.solve_time, 4),
        "problems": B,
        "problems_per_sec": round(B / max(res.solve_time, 1e-9), 1),
        "optimal": ok,
        "status_breakdown": breakdown,
        "non_optimal_members": non_opt[:16],  # cap: artifact readability
        "wall_s": round(dt, 4),
        **({"timing_note": timing_note} if timing_note else {}),
        "tol": 1e-8,
        # null until the baseline measurement actually succeeds — a
        # fabricated neutral 1.0 would read as "measured, no speedup".
        "vs_baseline": None,
    }
    try:
        # MEASURED full-loop baseline first (VERDICT round-4 item 1: no
        # sampling/extrapolation): scripts/run_batched_cpu_loop.py solves
        # all 1024 members one at a time through cpu-native on a quiet
        # host and records the artifact consumed here. Falls back to the
        # sampled estimate only when the artifact is absent or doesn't
        # match this row's config.
        import json as _json

        loop_art = os.path.join(_REPO, ".batched_cpu_loop.json")
        used_artifact = False
        if not quick and os.path.exists(loop_art):
            art = _json.load(open(loop_art))
            # the full config string must match — B alone would accept a
            # stale artifact measured on a different shape/seed
            expected_cfg = f"{B} x ({m}x{n}) seed=0 looped cpu-native"
            if art.get("config") == expected_cfg and art.get("n_optimal", 0) == B:
                base_s = art["sum_solve_s"]  # per-solve sum: contention-free
                row.update(
                    baseline_backend="cpu-native (loop, one LP at a time)",
                    baseline_sample=B,
                    baseline_measured_full_loop=True,
                    baseline_time_s=base_s,
                    baseline_artifact=".batched_cpu_loop.json",
                    vs_baseline=round(base_s / max(res.solve_time, 1e-9), 2),
                )
                _log(
                    f"  baseline cpu-native loop (MEASURED, all {B}): "
                    f"{base_s:.1f}s ({row['vs_baseline']}x)"
                )
                used_artifact = True
        if not used_artifact:
            sample = min(16, B) if quick else min(128, B)
            rng = __import__("numpy").random.default_rng(7)
            idx = rng.choice(B, size=sample, replace=False)
            probs = [batch.problem(int(i)) for i in idx]
            _solve_timed(probs[0], "cpu-native")  # warm any lazy init
            t0 = time.perf_counter()
            base_ok = 0
            for p in probs:
                rb = _solve_timed(p, "cpu-native")
                base_ok += rb.status.value == "optimal"
            t_sample = time.perf_counter() - t0
            per = t_sample / sample
            est = per * B
            row.update(
                baseline_backend="cpu-native (loop, one LP at a time)",
                baseline_sample=sample,
                baseline_sample_optimal=base_ok,
                baseline_per_problem_s=round(per, 4),
                baseline_time_est_s=round(est, 2),
                vs_baseline=round(est / max(res.solve_time, 1e-9), 2),
            )
            _log(
                f"  baseline cpu-native loop: {sample} sampled, "
                f"{per:.3f}s/problem -> est {est:.1f}s for {B} "
                f"({row['vs_baseline']}x)"
            )
    except Exception as e:  # baseline must never sink the bench
        _log(f"  batched baseline failed: {e}")
    return row


def _bench_serve(quick: bool) -> dict:
    """Serving-throughput row: drive the async batching SolveService with
    the standard random request stream and report the service's own
    telemetry — rps, latency percentiles, padding waste, and the warm
    recompile count (the zero-warm-recompile invariant as a bench
    figure). The cold wave warms every bucket program; the timed wave is
    the steady-state serving figure BENCH_SUITE tracks over rounds."""
    import numpy as _np

    from distributedlpsolver_tpu.backends.batched import bucket_cache_size
    from distributedlpsolver_tpu.models.generators import random_request_stream
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    n = 48 if quick else 200
    cfg = ServiceConfig(batch=8, flush_s=0.02)
    with SolveService(cfg) as svc:
        futs = [svc.submit(p) for p in random_request_stream(n, seed=21)]
        svc.drain(timeout=1200)
        cold = [f.result(timeout=60) for f in futs]
        cache0 = bucket_cache_size()
        t0 = time.perf_counter()
        futs = [svc.submit(p) for p in random_request_stream(n, seed=22)]
        svc.drain(timeout=1200)
        rs = [f.result(timeout=60) for f in futs]
        wall = time.perf_counter() - t0
        warm_recompiles = bucket_cache_size() - cache0
        stats = svc.stats()
    from distributedlpsolver_tpu.obs.stats import percentile

    lat = [r.total_ms for r in rs]
    ok = sum(r.status.value == "optimal" for r in rs)
    row = {
        "backend": "serve(batched bucket dispatch)",
        "requests": n,
        "optimal": ok,
        "cold_optimal": sum(r.status.value == "optimal" for r in cold),
        "time_s": round(wall, 4),
        "rps": round(n / max(wall, 1e-9), 2),
        "latency_ms_p50": round(percentile(lat, 50), 3),
        "latency_ms_p99": round(percentile(lat, 99), 3),
        "mean_padding_waste": round(
            float(_np.mean([r.padding_waste for r in rs])), 4
        ),
        "warm_recompiles": int(warm_recompiles),
        "overlap_ms_total": stats["overlap_ms_total"],
        "buckets": stats["buckets"],
        # Mixed-precision attribution: which precision schedule the
        # bucket programs ran (per-phase iteration totals by engine) and
        # how many IPM iterations each device while-trip fused — future
        # BENCH rows can attribute serving wins to the df32/fused-k
        # levers instead of guessing.
        "schedule": stats["schedule"],
        "phase_iters": stats["phase_iters"],
        "fused_iters": stats["fused_iters"],
        "tol": 1e-8,
        "vs_baseline": None,
    }
    _log(
        f"  serve: {n} requests at {row['rps']} rps warm, "
        f"p50={row['latency_ms_p50']:.0f}ms p99={row['latency_ms_p99']:.0f}ms, "
        f"waste={row['mean_padding_waste']:.2f}, "
        f"warm recompiles={warm_recompiles}, "
        f"schedule={row['schedule']} (phase iters {row['phase_iters']}), "
        f"fused_iters={row['fused_iters']}"
    )
    row["warm_start"] = _bench_serve_warm(quick)
    return row


def _bench_obs(args) -> list:
    """Tracing-overhead A/B: the steady-state serve shape (same stream,
    seeds, and service config as the in-process serve row) with the
    distributed-tracing layer OFF (null tracer, no contexts — the
    baseline every request pays today) vs ON (a live Chrome tracer plus
    a per-request root TraceContext threaded through submit, the full
    span-emission path the fleet aggregator consumes). Tracing is
    host-side bookkeeping by construction — the A/B pins the two
    figures that claim rests on: warm-path latency overhead (p50) and
    the warm recompile count (contexts must never reach program
    identity)."""
    import shutil as _shutil
    import tempfile as _tempfile

    from distributedlpsolver_tpu.backends.batched import bucket_cache_size
    from distributedlpsolver_tpu.models.generators import random_request_stream
    from distributedlpsolver_tpu.obs import trace as obs_trace
    from distributedlpsolver_tpu.obs.context import new_context
    from distributedlpsolver_tpu.obs.stats import percentile
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    n = 48 if args.quick else 200
    rows = []
    for mode in ("off", "on"):
        traced = mode == "on"
        tmpdir = prev = None
        trace_events = None
        if traced:
            tmpdir = _tempfile.mkdtemp(prefix="dlps-bench-obs-")
            prev = obs_trace.set_tracer(obs_trace.Tracer(
                os.path.join(tmpdir, "bench.trace.json"),
                process_name="bench-obs",
            ))
        try:
            with SolveService(ServiceConfig(batch=8, flush_s=0.02)) as svc:
                futs = [
                    svc.submit(p, trace=new_context() if traced else None)
                    for p in random_request_stream(n, seed=21)
                ]
                svc.drain(timeout=1200)
                cold_ok = sum(
                    f.result(timeout=60).status.value == "optimal"
                    for f in futs
                )
                cache0 = bucket_cache_size()
                t0 = time.perf_counter()
                futs = [
                    svc.submit(p, trace=new_context() if traced else None)
                    for p in random_request_stream(n, seed=22)
                ]
                svc.drain(timeout=1200)
                rs = [f.result(timeout=60) for f in futs]
                wall = time.perf_counter() - t0
                warm_recompiles = bucket_cache_size() - cache0
        finally:
            if traced:
                tracer = obs_trace.get_tracer()
                obs_trace.set_tracer(prev)
                tracer.close()
                try:
                    with open(tracer.path) as fh:
                        trace_events = len(json.load(fh)["traceEvents"])
                finally:
                    _shutil.rmtree(tmpdir, ignore_errors=True)
        lat = sorted(r.total_ms for r in rs)
        row = {
            "mode": f"tracing-{mode}",
            "requests": n,
            "optimal": sum(r.status.value == "optimal" for r in rs),
            "cold_optimal": cold_ok,
            "time_s": round(wall, 4),
            "rps": round(n / max(wall, 1e-9), 2),
            "latency_ms_p50": round(percentile(lat, 50), 3),
            "latency_ms_p99": round(percentile(lat, 99), 3),
            "warm_recompiles": int(warm_recompiles),
        }
        if traced:
            row["trace_events"] = trace_events
        rows.append(row)
        _log(
            f"  obs[{row['mode']}]: {n} requests at {row['rps']} rps, "
            f"p50={row['latency_ms_p50']:.1f}ms "
            f"p99={row['latency_ms_p99']:.1f}ms, "
            f"warm recompiles={warm_recompiles}"
            + (f", trace events={trace_events}" if traced else "")
        )
    off, on = rows
    base = max(off["latency_ms_p50"], 1e-9)
    on["p50_overhead_pct"] = round(
        100.0 * (on["latency_ms_p50"] - off["latency_ms_p50"]) / base, 2
    )
    base99 = max(off["latency_ms_p99"], 1e-9)
    on["p99_overhead_pct"] = round(
        100.0 * (on["latency_ms_p99"] - off["latency_ms_p99"]) / base99, 2
    )
    _log(
        f"  obs: tracing-on p50 overhead {on['p50_overhead_pct']:+.2f}% "
        f"(p99 {on['p99_overhead_pct']:+.2f}%)"
    )
    return rows


def _bench_serve_http(quick: bool, inproc_row: Optional[dict] = None) -> dict:
    """HTTP-path serving row: the same steady-state request stream as
    the in-process serve row, but submitted over the network plane
    (POST /v1/solve against a SolveHTTPServer on localhost) from
    concurrent client threads — so the network overhead (HTTP parse,
    JSON encode, socket round-trip, handler-thread dispatch) is
    attributed as the delta against the in-process row's figures."""
    import json as _json
    import threading as _threading
    import urllib.request as _urlreq

    from distributedlpsolver_tpu.backends.batched import bucket_cache_size
    from distributedlpsolver_tpu.models.generators import random_request_stream
    from distributedlpsolver_tpu.net import NetConfig, SolveHTTPServer
    from distributedlpsolver_tpu.obs.stats import percentile
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    n = 48 if quick else 200
    with SolveService(ServiceConfig(batch=8, flush_s=0.02)) as svc:
        server = SolveHTTPServer(svc, NetConfig()).start()
        # Cold wave in-process: warm every bucket program so the HTTP
        # wave measures the network path, not XLA.
        futs = [svc.submit(p) for p in random_request_stream(n, seed=21)]
        svc.drain(timeout=1200)
        for f in futs:
            f.result(timeout=60)
        cache0 = bucket_cache_size()

        problems = list(random_request_stream(n, seed=22))
        lat: list = []
        codes: list = []
        lock = _threading.Lock()

        def client(idx0, step):
            for i in range(idx0, n, step):
                p = problems[i]
                body = _json.dumps(
                    {
                        "problem": {
                            "c": p.c.tolist(),
                            "A": p.A.tolist(),
                            "b": p.rlb.tolist(),
                        },
                        "include_x": False,
                    }
                ).encode()
                req = _urlreq.Request(
                    server.url + "/v1/solve", data=body,
                    headers={"Content-Type": "application/json"},
                )
                t0 = time.perf_counter()
                try:
                    with _urlreq.urlopen(req, timeout=120) as r:
                        out = _json.loads(r.read())
                    code = 200 if out.get("status") == "optimal" else -1
                except Exception:
                    code = -2
                with lock:
                    lat.append((time.perf_counter() - t0) * 1e3)
                    codes.append(code)

        n_clients = 8
        t0 = time.perf_counter()
        threads = [
            _threading.Thread(target=client, args=(i, n_clients))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200)
        wall = time.perf_counter() - t0
        warm_recompiles = bucket_cache_size() - cache0
        server.shutdown()
    row = {
        "backend": "serve-http(localhost HTTP front-end)",
        "requests": n,
        "optimal": sum(c == 200 for c in codes),
        "clients": n_clients,
        "time_s": round(wall, 4),
        "rps": round(n / max(wall, 1e-9), 2),
        "latency_ms_p50": round(percentile(lat, 50), 3),
        "latency_ms_p99": round(percentile(lat, 99), 3),
        "warm_recompiles": int(warm_recompiles),
        "tol": 1e-8,
    }
    if inproc_row:
        # Network overhead, attributed: the HTTP row against the
        # in-process row it rode next to.
        row["inproc_rps"] = inproc_row["rps"]
        row["http_overhead_ms_p50"] = round(
            row["latency_ms_p50"] - inproc_row["latency_ms_p50"], 3
        )
    _log(
        f"  serve-http: {n} requests at {row['rps']} rps over "
        f"{n_clients} clients, p50={row['latency_ms_p50']:.0f}ms "
        f"p99={row['latency_ms_p99']:.0f}ms, warm recompiles="
        f"{warm_recompiles}"
        + (
            f", in-process rps={row['inproc_rps']} "
            f"(http p50 overhead {row['http_overhead_ms_p50']:+.1f}ms)"
            if inproc_row
            else ""
        )
    )
    return row


def _bench_serve_warm(quick: bool) -> dict:
    """Warm-start & amortization sub-row: drive the seeded CORRELATED
    stream (same models, perturbed b/c — models/generators.
    correlated_request_stream) through one service with the fingerprint
    cache on, after a cold leg that populates it. Reports median
    iterations-per-request and p50/p99 latency warm-vs-cold, the
    cache-hit ratio, safeguard rejections, and the zero-warm-recompile
    check across the warm leg — the measurements the warm layer is
    accepted on."""
    from distributedlpsolver_tpu.backends.batched import bucket_cache_size
    from distributedlpsolver_tpu.models.generators import (
        correlated_request_stream,
    )
    from distributedlpsolver_tpu.obs.stats import percentile
    from distributedlpsolver_tpu.serve import ServiceConfig, SolveService

    n_cold = 24 if quick else 64
    n_warm = 32 if quick else 128
    with SolveService(ServiceConfig(batch=8, flush_s=0.02)) as svc:
        futs = [
            svc.submit(p)
            for p in correlated_request_stream(n_cold, seed=31)
        ]
        svc.drain(timeout=1200)
        cold_leg = [f.result(timeout=60) for f in futs]
        cache0 = bucket_cache_size()
        t0 = time.perf_counter()
        futs = [
            svc.submit(p)
            for p in correlated_request_stream(
                n_warm, seed=31, offset=n_cold
            )
        ]
        svc.drain(timeout=1200)
        rs = [f.result(timeout=60) for f in futs]
        wall = time.perf_counter() - t0
        warm_recompiles = bucket_cache_size() - cache0
        stats = svc.stats()

    warm_rs = [r for r in rs if r.warm == "warm"]
    # Cold baseline over BOTH legs: at a 100% hit ratio the warm leg
    # alone has no cold members left to compare against.
    cold_rs = [r for r in cold_leg + rs if r.warm != "warm"]
    row = {
        "requests": n_warm,
        "optimal": sum(r.status.value == "optimal" for r in rs),
        "time_s": round(wall, 4),
        "warm_requests": len(warm_rs),
        "hit_ratio": round(len(warm_rs) / max(n_warm, 1), 4),
        "rejected": sum(1 for r in rs if r.warm == "rejected"),
        "iters_median_warm": percentile([r.iterations for r in warm_rs], 50),
        "iters_median_cold": percentile([r.iterations for r in cold_rs], 50),
        "latency_ms_p50_warm": round(
            percentile([r.total_ms for r in warm_rs], 50), 3
        ),
        "latency_ms_p99_warm": round(
            percentile([r.total_ms for r in warm_rs], 99), 3
        ),
        "latency_ms_p50_cold": round(
            percentile([r.total_ms for r in cold_rs], 50), 3
        ),
        "latency_ms_p99_cold": round(
            percentile([r.total_ms for r in cold_rs], 99), 3
        ),
        "warm_recompiles": int(warm_recompiles),
        "warm_cache": stats["warm_cache"],
    }
    _log(
        f"  serve warm-start: {row['warm_requests']}/{n_warm} warm "
        f"(hit {row['hit_ratio']:.0%}, {row['rejected']} rejected), "
        f"median iters {row['iters_median_cold']:.0f} cold -> "
        f"{row['iters_median_warm']:.0f} warm, "
        f"p50 {row['latency_ms_p50_cold']:.0f} -> "
        f"{row['latency_ms_p50_warm']:.0f} ms, "
        f"warm recompiles={warm_recompiles}"
    )
    return row


def _bench_fixtures(quick: bool) -> list:
    """Vendored golden MPS fixtures (+ a ≥10 MB generated file) as suite
    rows: parse → auto-dispatch solve → check the hand-derived optimum
    (VERDICT round 2 item 8 — the realism available without Netlib
    egress; the expected values are derived by hand in
    tests/test_fixtures.py and cross-checked against HiGHS there)."""
    from distributedlpsolver_tpu.io.mps import read_mps, write_mps

    rows = []
    for name, opt in (("quirks.mps", 12.0), ("maximize.mps", 14.0)):
        path = os.path.join(_REPO, "tests", "fixtures", name)
        t0 = time.perf_counter()
        p = read_mps(path)
        t_parse = time.perf_counter() - t0
        r = _solve_timed(p, "auto")
        matches = (
            r.status.value == "optimal"
            and abs(r.objective - opt) <= 1e-6 * max(1.0, abs(opt))
        )
        _log(f"  fixture {name}: {r.summary()} (expected obj {opt})")
        rows.append({
            "config": f"fixture {name}",
            "backend": r.backend,
            "time_s": round(r.solve_time, 4),
            "iters": int(r.iterations),
            "status": r.status.value,
            "tol": 1e-8,
            "parse_s": round(t_parse, 4),
            "objective": round(float(r.objective), 9),
            "expected_objective": opt,
            "matches_known_optimum": bool(matches),
            "vs_baseline": None,
        })
    if quick:
        return rows
    # ≥10 MB round-trip realism: generate, WRITE through the package's
    # writer, parse it back, and the solved objective must match the
    # in-memory problem's solve bit-for-bit-ish (same solver, same tol).
    import tempfile

    from distributedlpsolver_tpu.models.generators import random_dense_lp

    p = random_dense_lp(512, 1024, seed=4)
    with tempfile.NamedTemporaryFile("w", suffix=".mps", delete=False) as fh:
        tmp = fh.name
    try:
        write_mps(p, tmp)
        size_mb = os.path.getsize(tmp) / 1e6
        t0 = time.perf_counter()
        q = read_mps(tmp)
        t_parse = time.perf_counter() - t0
        _solve_timed(q, "auto", max_iter=3)  # compile warm-up
        r = _solve_timed(q, "auto")
        r_direct = _solve_timed(p, "auto")
        agree = abs(r.objective - r_direct.objective) <= 1e-7 * (
            1.0 + abs(r_direct.objective)
        )
        _log(
            f"  big file: {size_mb:.1f} MB parsed in {t_parse:.2f}s; "
            f"{r.summary()} (direct-solve agreement: {agree})"
        )
        rows.append({
            "config": "generated dense 512x1024 via 10MB+ MPS round-trip",
            "backend": r.backend,
            "time_s": round(r.solve_time, 4),
            "iters": int(r.iterations),
            "status": r.status.value,
            "tol": 1e-8,
            "file_mb": round(size_mb, 1),
            "parse_s": round(t_parse, 3),
            "agrees_with_direct_solve": bool(agree),
            "vs_baseline": None,
        })
    finally:
        os.unlink(tmp)
    return rows


def run_suite(args) -> list:
    """All five reference benchmark configs (BASELINE.json:7-11), plus
    the golden-fixture rows."""
    from distributedlpsolver_tpu.models.generators import (
        block_angular_lp,
        random_dense_lp,
        random_general_lp,
    )

    q = args.quick
    accel = args.backend
    rows = []

    def add(config, row):
        # Cumulative-to-here metrics snapshot: each row records the
        # observability state at the time it completed.
        row = {"config": config, **row, "metrics": _obs_row(args.platform)}
        rows.append(row)
        _log(json.dumps(row))

    # 1. afiro-class tiny dense (BASELINE.json:7) — 27x51, general form.
    # Measured through --backend auto: structure/size-aware dispatch is
    # the production answer for a dispatch-bound tiny LP (a tunneled
    # accelerator pays ~0.5 s where the CPU path takes ~10 ms); the row
    # records which backend auto picked.
    _log("[1/7] afiro-class dense 27x51 (auto dispatch)")
    add(
        "afiro-like general LP 27x51",
        _bench_one(random_general_lp(27, 51, seed=0), "auto", "cpu"),
    )

    # 2. pds-02/pds-10-class block-angular (BASELINE.json:8) — the
    # reference's 4-rank row-partitioned configs; here the Schur-complement
    # block backend vs the dense CPU path.
    _log("[2/7] pds-class block-angular (Schur backend)")
    shape = (4, 24, 48, 12) if q else (4, 64, 160, 32)
    add(
        f"pds-02-like block_angular{shape}",
        _bench_one(
            block_angular_lp(*shape, seed=1, sparse=False), "block", "cpu-native"
        ),
    )

    # 3. Random dense full-Cholesky path (BASELINE.json:9; m=10k n=50k in
    # the reference — scaled to fit a single v5e's HBM and test budget,
    # --full restores the reference shape). The default auto two-phase
    # schedule (f32 Pallas phase + f64 finish) does the mixed precision;
    # forcing single-phase f32 here stalls short of the 1e-8 gap.
    m, n = (128, 320) if q else ((10_000, 50_000) if args.full else (2_048, 10_240))
    _log(f"[3/7] random dense {m}x{n} (two-phase mixed precision)")
    row3 = _bench_one(
        random_dense_lp(m, n, seed=2),
        accel,
        "cpu-native" if q else None,  # in-suite CPU solve only at quick size
    )
    if (m, n) == (2048, 10240) and row3.get("vs_baseline") is None:
        # MEASURED end-to-end dense baseline (VERDICT round-4 item 3):
        # scripts/run_dense2k_cpu.py solved this exact instance
        # (seed=2) through cpu-native on a quiet host — 839 s, 26
        # iters, OPTIMAL — far too long to re-run inside every suite,
        # so the artifact is consumed like the batched loop baseline.
        art_p = os.path.join(_REPO, ".dense2k_cpu.json")
        if os.path.exists(art_p):
            art = json.load(open(art_p))
            if (
                art.get("config") == f"random dense {m}x{n} seed=2"
                and art.get("status") == "optimal"
            ):
                row3.update(
                    baseline_backend="cpu-native (end-to-end measured)",
                    baseline_time_s=art["solve_s"],
                    baseline_process_cpu_s=art["process_cpu_s"],
                    baseline_artifact=".dense2k_cpu.json",
                    vs_baseline=round(
                        art["solve_s"] / max(row3["time_s"], 1e-9), 1
                    ),
                )
    add(f"random dense {m}x{n}", row3)

    # 4. Large-sparse class (BASELINE.json:10, neos3/stormG2-like):
    # stormG2 IS block-angular (stochastic program). The stand-in arrives
    # HINT-LESS (like a real MPS file); structure detection
    # (models/structure.py) recovers the partition — run explicitly here so
    # the row measures the same detect→Schur path on every host platform
    # (auto's platform rules would divert to cpu-native on a CPU-only box)
    # — and the Schur backend executes it, vs the sparse-direct baseline.
    _log("[4/7] large sparse, hint-less (structure detection → Schur backend)")
    # Non-quick shape is the stormG2-class scale target (VERDICT round 2
    # item 4): ≥20k rows, hundreds of natural blocks — detection recovers
    # K=256 and the Schur backend must beat cpu-sparse decisively
    # (measured 2026-07-31: 10.2 s vs 187 s, 18×).
    shape, dens = ((4, 24, 48, 12), 0.15) if q else ((256, 80, 160, 48), 0.08)
    sparse_lp = block_angular_lp(*shape, seed=3, sparse=True, density=dens)
    sparse_lp.block_structure = None  # what a real file looks like
    from distributedlpsolver_tpu.models.structure import detect_block_structure

    t_detect = time.perf_counter()
    hint = detect_block_structure(sparse_lp)
    t_detect = time.perf_counter() - t_detect
    if hint is not None:
        sparse_lp.block_structure = hint
        row = _bench_one(sparse_lp, "block", "cpu-sparse")
        row["detect_s"] = round(t_detect, 4)
        row["detected_blocks"] = hint["num_blocks"]
    else:  # detection declined: honest fallback, still measured
        row = _bench_one(sparse_lp, "cpu-sparse", "cpu")
    add(f"stormG2-like sparse block_angular{shape} (hint-less)", row)

    # 4b. UNSTRUCTURED sparse (BASELINE.json:10, the neos3 half of the
    # class): a uniformly random pattern defeats detection, and the
    # measured routing decision (scripts/run_neos3.py) sends it to the
    # sparse-direct host backend. The row exercises exactly that route
    # through auto so a routing regression shows up as a changed
    # backend name. No baseline: the only honest comparator would be
    # the dense-LAPACK host path, whose m²n-per-iteration cost at this
    # shape is hours — the cross-executor measurement at 1e-8 lives in
    # scripts/run_neos3.py's artifact instead.
    _log("[4b] unstructured sparse, detection-defeating (auto -> cpu-sparse)")
    from distributedlpsolver_tpu.models.generators import random_sparse_lp

    # Sized for the suite budget: _bench_one runs THREE full solves
    # (warm-up + best-of-two), and the sparse-direct factorization's
    # fill-in makes an 8000x16000 instance a ~20-minute-per-solve row
    # (observed) — the scale-class record lives in .neos3_sparse.json,
    # this row pins the ROUTE end-to-end.
    ushape = (400, 800, 0.01) if q else (2000, 4000, 0.002)
    add(
        f"neos3-like unstructured sparse {ushape[0]}x{ushape[1]}",
        _bench_one(
            random_sparse_lp(ushape[0], ushape[1], density=ushape[2], seed=0),
            "auto", None,
        ),
    )

    # 5. Batched concurrent LPs (BASELINE.json:11).
    _log("[5/7] batched 1024x(128,512) vmap solve")
    add("batched 1024x(128x512)" if not q else "batched 32x(16x40)", _bench_batched(q))

    # 5b. Serving throughput over the same batched machinery (the
    # continuous-batching front-end BENCH_SUITE tracks as a trajectory).
    _log("[6/7] serve throughput (async batching solve service)")
    add(f"serve throughput {48 if q else 200} requests", _bench_serve(q))

    # 6. Golden MPS fixtures + big-file round trip (real-file realism).
    _log("[7/7] golden MPS fixtures (hand-derived optima)")
    fixture_rows = _bench_fixtures(q)
    rows.extend(fixture_rows)
    for row in fixture_rows:
        _log(json.dumps(row))

    return rows


def run_scale(args) -> list:
    """Pass/fail regression tier for the 10k-scale machinery (VERDICT
    round 3 item 7): the scale behaviors dense.py's design encodes
    (two-phase + PCG handoff, host-LAPACK endgame, direction-level primal
    closure) were established by one-off probe scripts; this tier freezes
    them into envelopes that fail loudly if they regress. Referenced from
    BASELINE.md; run once per round: ``python bench.py --scale``.

    Envelopes (TPU; wall-clock checks skip on other platforms where the
    emulated-f64 cost model doesn't apply):
      1. dense 2048x10240 via the auto schedule: OPTIMAL at 1e-8,
         pinf <= 1e-8, solve <= 3 s warm (measured 2026-07-31: ~0.7 s;
         3 s = 4x headroom over dispatch-latency noise).
      2. dense 1024x5120 with the endgame FORCED (the 10k finish path at
         a minutes-not-hours size): OPTIMAL with final pinf <= 1e-12 —
         the host-factor + primal-closure guarantee (entry pinf ~1e-8
         must DROP through the endgame, not floor).
      3. batched 1024x(128,512) headline: all 1024 members OPTIMAL,
         warm solve <= 240 s (measured ~116 s).
      4. storm-20k hint-less block-angular headline: detection recovers
         K=256, Schur solve OPTIMAL <= 30 s warm (measured 6.3-10.2 s).
    """
    import jax

    from distributedlpsolver_tpu.backends import dense as D
    from distributedlpsolver_tpu.models.generators import random_dense_lp

    on_tpu = jax.default_backend() == "tpu"
    rows = []

    _log("[scale 1/4] dense 2048x10240 auto schedule (envelope: optimal, "
         "pinf<=1e-8, warm solve<=3s)")
    p = random_dense_lp(2048, 10240, seed=2)
    # Warm-up at DEFAULT config: buffer caps are static jit keys bucketed
    # from n_phases·max_iter (core.buffer_cap), so a small-max_iter
    # warm-up would compile a different (never reused) bucket and the
    # timed solve would pay the real compile inside its 3 s envelope.
    # _solve_timed: one tunnel drop must not crash the whole tier.
    # Best-of-two like the suite rows (ADVICE round 4): the tunneled
    # worker shows one-off ~8× slowness on warm programs, and a single
    # sample against a 3 s envelope would fail the tier spuriously.
    _solve_timed(p, args.backend)
    r = _solve_timed(p, args.backend)
    r2 = _solve_timed(p, args.backend)
    if r2.solve_time < r.solve_time:
        r = r2
    row = {
        "check": "dense_2048x10240",
        "status": r.status.value,
        "time_s": round(r.solve_time, 3),
        "iters": int(r.iterations),
        "rel_gap": float(r.rel_gap),
        "pinf": float(r.pinf),
        "envelope": {"status": "optimal", "pinf_max": 1e-8,
                     "time_s_max": 3.0 if on_tpu else None},
        "pass": bool(
            r.status.value == "optimal"
            and r.pinf <= 1e-8
            and (not on_tpu or r.solve_time <= 3.0)
        ),
    }
    rows.append(row)
    _log(json.dumps(row))

    if not on_tpu:
        # The endgame only triggers from the two-phase+PCG schedule, which
        # is TPU-only (off-TPU, device f64 is LAPACK-grade and the direct
        # path runs) — forcing it here would test a path production never
        # takes on this platform and fail spuriously. The batched and
        # storm headline envelopes are wall-clock envelopes calibrated on
        # the chip, so they skip off-TPU too (their MATH is covered at
        # small scale by tier-1 tests).
        for check, why in (
            ("dense_1024x5120_forced_endgame",
             "endgame is a TPU-only path (emulated-f64 finish)"),
            ("batched_1024x128x512",
             "wall-clock envelope calibrated on the TPU chip"),
            ("storm20k_block_angular",
             "wall-clock envelope calibrated on the TPU chip"),
        ):
            row2 = {"check": check, "skipped": True,
                    "reason": f"{why}; run this tier on the TPU chip",
                    "pass": True}
            rows.append(row2)
            _log(json.dumps(row2))
        return rows

    _log("[scale 2/4] dense 1024x5120 forced endgame (envelope: optimal, "
         "final pinf<=1e-12)")
    entries_save = D.DenseJaxBackend._ENDGAME_ENTRIES
    try:
        D.DenseJaxBackend._ENDGAME_ENTRIES = 1  # force the 10k finish path
        be = D.DenseJaxBackend()
        p2 = random_dense_lp(1024, 5120, seed=2)
        r2 = _solve_timed(p2, be, solve_mode="pcg", max_iter=120)
    finally:
        D.DenseJaxBackend._ENDGAME_ENTRIES = entries_save
    row2 = {
        "check": "dense_1024x5120_forced_endgame",
        "status": r2.status.value,
        "time_s": round(r2.solve_time, 3),
        "iters": int(r2.iterations),
        "rel_gap": float(r2.rel_gap),
        "pinf": float(r2.pinf),
        "dinf": float(r2.dinf),
        # Accepted endgame iterations only — a raw row count would also
        # count bad-step retry attempts (ADVICE round 4).
        "endgame_iters": sum(
            1 for t in getattr(be, "endgame_timings", [])
            if "t_step" in t and not t.get("bad")
        ),
        "envelope": {"status": "optimal", "pinf_max": 1e-12},
        "pass": bool(r2.status.value == "optimal" and r2.pinf <= 1e-12),
    }
    rows.append(row2)
    _log(json.dumps(row2))

    # 3. Batched headline config (BASELINE.json:11; VERDICT "What's weak"
    # #3 — the 2.06×-vs-CPU-loop figure had no regression envelope).
    # Measured 2026-08-01: ~116 s warm with all 1024 members optimal;
    # 240 s = ~2× headroom over tunnel noise.
    _log("[scale 3/4] batched 1024x(128,512) vmap solve (envelope: "
         "1024/1024 optimal, solve<=240s warm)")
    from distributedlpsolver_tpu.backends.batched import solve_batched
    from distributedlpsolver_tpu.models.generators import random_batched_lp

    batch = random_batched_lp(1024, 128, 512, seed=0)
    solve_batched(batch, max_iter=3)  # compile warm-up (full-size programs)
    # One full untimed solve: the final-phase compaction programs
    # (256→…→32) only compile once actives drain — see _bench_batched.
    solve_batched(batch)
    r3 = solve_batched(batch)
    r3b = solve_batched(batch)
    if r3b.solve_time < r3.solve_time:
        r3 = r3b
    row3 = {
        "check": "batched_1024x128x512",
        "optimal": int(r3.n_optimal),
        "problems": len(r3.status),
        "time_s": round(r3.solve_time, 3),
        "envelope": {"n_optimal": 1024, "time_s_max": 240.0},
        "pass": bool(r3.n_optimal == 1024 and r3.solve_time <= 240.0),
    }
    rows.append(row3)
    _log(json.dumps(row3))

    # 4. storm-20k headline config (scripts/run_storm20k.py, VERDICT
    # round 2 item 4): hint-less ≥20k-row block-angular — detection must
    # recover K=256 and the Schur path must stay in its measured class
    # (6.3–10.2 s observed; 30 s = ~3× headroom).
    _log("[scale 4/4] storm-20k hint-less detect→Schur (envelope: optimal, "
         "K=256 detected, solve<=30s warm)")
    from distributedlpsolver_tpu.models.generators import block_angular_lp
    from distributedlpsolver_tpu.models.structure import detect_block_structure

    p4 = block_angular_lp(256, 80, 160, 48, seed=3, sparse=True, density=0.08)
    p4.block_structure = None  # what a real file looks like
    hint = detect_block_structure(p4)
    detected = int(hint["num_blocks"]) if hint else 0
    row4 = {
        "check": "storm20k_block_angular",
        "detected_blocks": detected,
        "envelope": {"status": "optimal", "detected_blocks": 256,
                     "time_s_max": 30.0},
    }
    if hint is None:
        row4.update(status="detection_declined")
        row4["pass"] = False
    else:
        p4.block_structure = hint
        _solve_timed(p4, "block", max_iter=3)  # compile warm-up
        r4 = _solve_timed(p4, "block")
        r4b = _solve_timed(p4, "block")
        if r4b.solve_time < r4.solve_time:
            r4 = r4b
        row4.update(
            status=r4.status.value,
            time_s=round(r4.solve_time, 3),
            iters=int(r4.iterations),
            rel_gap=float(r4.rel_gap),
        )
        row4["pass"] = bool(
            r4.status.value == "optimal"
            and detected == 256
            and r4.solve_time <= 30.0
        )
    rows.append(row4)
    _log(json.dumps(row4))
    return rows


def _bench_sparse(args) -> list:
    """Huge-sparse tier rows (``--sparse``): the SAME storm-profile
    instance through each engine of the tier so the win is attributable —
    matrix-free inexact IPM (PCG normal equations, 1e-8), restarted PDHG
    (matrix-free first-order, its 1e-4 tier), and the dense baseline
    (only where the dense assembly fits — at storm scale the row records
    WHY it is absent instead of silently shrinking the instance).
    Columns include density/nnz/cg_iters/precond so BENCH_SPARSE.json
    tracks preconditioner quality over rounds, not just wall clock."""
    from distributedlpsolver_tpu.backends.base import get_backend
    from distributedlpsolver_tpu.models.generators import storm_sparse_lp

    K = 32 if args.quick else 320
    p = storm_sparse_lp(K, 64, 96, 64, seed=1)
    m, n = p.A.shape
    nnz = int(p.A.nnz)
    base = {
        "family": "sparse",
        "instance": p.name,
        "m": m,
        "n": n,
        "nnz": nnz,
        "density": round(nnz / (m * n), 6),
    }
    rows = []

    def add(row):
        row["platform"] = args.platform
        rows.append(row)
        _log(json.dumps(row))

    # 1. matrix-free inexact IPM at full tolerance.
    be = get_backend("sparse-iterative")
    r = _solve_timed(p, be, tol=1e-8, max_iter=200)
    rep = be.cg_report()
    add(
        dict(
            base,
            engine="sparse-iterative",
            tol=1e-8,
            status=r.status.value,
            iters=int(r.iterations),
            cg_iters=int(rep["cg_iters"]),
            precond=rep["precond"],
            time_s=round(r.solve_time, 4),
            setup_s=round(r.setup_time, 4),
            max_operand_mb=round(be.max_operand_nbytes() / 1e6, 2),
        )
    )

    # 2. restarted PDHG at its tolerance tier (matrix-free first-order).
    r = _solve_timed(p, "pdlp", tol=1e-4)
    add(
        dict(
            base,
            engine="pdhg",
            tol=1e-4,
            status=r.status.value,
            iters=int(r.iterations),
            time_s=round(r.solve_time, 4),
            setup_s=round(r.setup_time, 4),
        )
    )

    # 3. dense baseline on the SAME instance — only while the dense
    # assembly fits (~256 MB f64); past that the row says so explicitly.
    if m * n <= 1 << 25:
        r = _solve_timed(p, "cpu-native", tol=1e-8)
        add(
            dict(
                base,
                engine="dense(cpu-native)",
                tol=1e-8,
                status=r.status.value,
                iters=int(r.iterations),
                time_s=round(r.solve_time, 4),
                setup_s=round(r.setup_time, 4),
            )
        )
    else:
        add(
            dict(
                base,
                engine="dense(cpu-native)",
                tol=1e-8,
                status="skipped",
                skip_reason=(
                    f"dense assembly would be {m * n * 8 / 1e9:.1f} GB "
                    "(the arena this tier exists to avoid)"
                ),
            )
        )

    # 4. Distributed row family (row-sharded matrix-free tier): the
    # SAME storm-profile instance on 1 device vs every N-way row mesh
    # this host can form. Per-device max live operand bytes is THE
    # column (the ≈1/N law the tier exists for); psum_per_iter makes
    # the communication cost explicit — one n-vector all-reduce per CG
    # iteration, regardless of N.
    import jax as _jax

    from distributedlpsolver_tpu.backends.sparse_iterative import (
        SparseIterativeBackend,
    )
    from distributedlpsolver_tpu.parallel import mesh as mesh_lib

    Kd = 8 if args.quick else 64
    pd_spec = dict(
        scenarios=Kd, block_m=32, block_n=48, first_stage_n=24, seed=3
    )

    def _pd():
        return storm_sparse_lp(
            Kd, block_m=32, block_n=48, first_stage_n=24, seed=3
        )

    md, nd = _pd().A.shape
    dbase = {"family": "sparse-distributed", "instance": _pd().name,
             "m": md, "n": nd}
    ndev = len(_jax.devices())
    for width in [1] + [w for w in (2, 4, 8) if w <= ndev]:
        if width == 1:
            be = SparseIterativeBackend()
        else:
            mesh = mesh_lib.make_mesh(
                (width,),
                axis_names=("batch",),
                devices=_jax.devices()[:width],
            )
            be = SparseIterativeBackend(mesh=mesh)
        try:
            r = _solve_timed(_pd(), be, tol=1e-8, max_iter=200)
            rep = be.cg_report()
            add(
                dict(
                    dbase,
                    engine="sparse-iterative",
                    devices=width,
                    shards=int(rep["shards"]),
                    psum_per_iter=int(rep["psum_per_iter"]),
                    tol=1e-8,
                    status=r.status.value,
                    iters=int(r.iterations),
                    cg_iters=int(rep["cg_iters"]),
                    precond=rep["precond"],
                    time_s=round(r.solve_time, 4),
                    max_operand_mb=round(be.max_operand_nbytes() / 1e6, 3),
                    max_operand_per_device_mb=round(
                        be.max_operand_nbytes(per_device=True) / 1e6, 3
                    ),
                )
            )
        except Exception as e:
            add(
                dict(
                    dbase,
                    engine="sparse-iterative",
                    devices=width,
                    status="failed",
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
            )

    # 4b. 2-process world through the launcher (the multi-host seam):
    # same instance, row shards spanning a process boundary. Best-effort
    # — the CPU harness transport is lossy by design; a failed world is
    # recorded, not fatal.
    try:
        import tempfile

        from distributedlpsolver_tpu.distributed.launcher import run_world

        with tempfile.TemporaryDirectory(prefix="bench-sprows-") as wd:
            res = run_world(
                "sparse_rows",
                dict(pd_spec, tol=1e-8),
                world_size=2,
                workdir=wd,
                local_devices=2,
                timeout=600,
            )
        out0 = res[0]
        add(
            dict(
                dbase,
                engine="sparse-iterative",
                devices="2proc x 2dev",
                shards=int(out0["shards"]),
                psum_per_iter=int(out0["psum_per_iter"]),
                tol=1e-8,
                status=out0["status"],
                iters=int(out0["iterations"]),
                cg_iters=int(out0["cg_iters"]),
                precond=out0["precond"],
                max_operand_per_device_mb=round(
                    out0["max_operand_per_device"] / 1e6, 3
                ),
                ranks_agree=len(
                    {o["objective"] for o in res.values()}
                ) == 1,
            )
        )
    except Exception as e:
        add(
            dict(
                dbase,
                engine="sparse-iterative",
                devices="2proc x 2dev",
                status="failed",
                error=f"{type(e).__name__}: {str(e)[:200]}",
            )
        )

    # 5. ILDL-vs-Jacobi on the unstructured endgame (the instance that
    # used to degrade to cpu-sparse): jacobi's honest failure next to
    # auto's mid-solve escalation to incomplete-LDLᵀ, cg_iters side by
    # side so the preconditioner win is attributable over rounds.
    from distributedlpsolver_tpu.models.generators import netlib_sparse_lp

    pu = netlib_sparse_lp(120, 220, seed=10)
    ubase = {
        "family": "sparse-ildl",
        "instance": pu.name,
        "m": int(pu.A.shape[0]),
        "n": int(pu.A.shape[1]),
    }
    ildl_pair = {}
    for label, kw in (("jacobi", {"precond": "jacobi"}), ("auto", {})):
        be = SparseIterativeBackend(**kw)
        try:
            r = _solve_timed(
                netlib_sparse_lp(120, 220, seed=10), be, tol=1e-8,
                _retries=0,
            )
            rep = be.cg_report()
            row = dict(
                ubase,
                engine=f"sparse-iterative({label})",
                tol=1e-8,
                status=r.status.value,
                iters=int(r.iterations),
                cg_iters=int(rep["cg_iters"]),
                precond=rep["precond"],
                time_s=round(r.solve_time, 4),
            )
            ildl_pair[label] = row
            add(row)
        except Exception as e:
            row = dict(
                ubase,
                engine=f"sparse-iterative({label})",
                status="failed",
                error=f"{type(e).__name__}: {str(e)[:200]}",
            )
            ildl_pair[label] = row
            add(row)
    j, a = ildl_pair.get("jacobi", {}), ildl_pair.get("auto", {})

    def _cg_rate(row):
        if row.get("cg_iters") and row.get("iters"):
            return round(row["cg_iters"] / row["iters"], 1)
        return None

    jr, ar = _cg_rate(j), _cg_rate(a)
    add(
        dict(
            ubase,
            engine="ildl-vs-jacobi",
            jacobi_status=j.get("status"),
            jacobi_cg_iters=j.get("cg_iters"),
            jacobi_cg_per_ipm_iter=jr,
            ildl_status=a.get("status"),
            ildl_cg_iters=a.get("cg_iters"),
            ildl_cg_per_ipm_iter=ar,
            ildl_engaged=a.get("precond") == "ildl",
            # The win: ildl finishes where jacobi faulted, at a strictly
            # lower CG cost per IPM iteration (totals are not comparable
            # — jacobi died early, ildl ran the full endgame).
            ildl_wins=bool(
                a.get("status") == "optimal"
                and (j.get("status") != "optimal" or (jr or 0) > (ar or 0))
                and (jr is None or ar is None or ar < jr)
            ),
        )
    )
    return rows


def _bench_scenario(args) -> list:
    """Stochastic scenario tier rows (``--scenario``): the SAME
    two-stage storm instance through each engine that can hold it —
    the scenario-decomposed IPM (batched per-scenario Schur + arrow
    linking solve), the sparse-iterative rung on the lowered
    block-angular form (the degradation target, bordered-Woodbury
    preconditioner), and the dense baseline on the lowered form where
    its assembly fits. Columns carry K, the schur/link wall split, and
    peak operand bytes so BENCH_SCENARIO.json tracks how the
    decomposition scales in K across rounds."""
    from distributedlpsolver_tpu.backends import scenario as scn
    from distributedlpsolver_tpu.backends.base import get_backend
    from distributedlpsolver_tpu.models.problem import to_interior_form
    from distributedlpsolver_tpu.models.scenario import (
        scenario_k_bucket,
        two_stage_storm,
    )

    K = 16 if args.quick else 128
    slp = two_stage_storm(
        K, block_m=24, block_n=36, first_stage_n=24, first_stage_m=8,
        seed=1,
    )
    lowered = slp.to_block_angular()
    m, n = lowered.A.shape
    base = {
        "family": "scenario",
        "instance": slp.name,
        "K": K,
        "scenario_bucket": scenario_k_bucket(K),
        "m": m,
        "n": n,
        "nnz": int(lowered.A.nnz),
    }
    rows = []

    def add(row):
        row["platform"] = args.platform
        rows.append(row)
        _log(json.dumps(row))

    # 1. Scenario-decomposed IPM (warm-up first so the timed figure is
    # the warm-program number every later solve in the bucket pays).
    be = get_backend("scenario")
    r = _solve_timed(lowered, be, tol=1e-8)
    rep = scn.last_solve_report()
    add(
        dict(
            base,
            engine="scenario",
            tol=1e-8,
            status=r.status.value,
            iters=int(r.iterations),
            time_s=round(r.solve_time, 4),
            setup_s=round(r.setup_time, 4),
            schur_ms=round(float(rep.get("schur_ms", 0.0)), 3),
            link_ms=round(float(rep.get("link_ms", 0.0)), 3),
            cg_iters=int(rep.get("cg_iters", 0)),
            max_operand_mb=round(be.operand_nbytes() / 1e6, 2),
        )
    )

    # 2. Lowered block-angular form through the matrix-free inexact IPM
    # (the degradation rung; its bordered preconditioner consumes the
    # same two_stage pattern).
    be_si = get_backend("sparse-iterative")
    r = _solve_timed(lowered, be_si, tol=1e-8, max_iter=200)
    rep_si = be_si.cg_report()
    add(
        dict(
            base,
            engine="sparse-iterative(lowered)",
            tol=1e-8,
            status=r.status.value,
            iters=int(r.iterations),
            cg_iters=int(rep_si["cg_iters"]),
            precond=rep_si["precond"],
            time_s=round(r.solve_time, 4),
            setup_s=round(r.setup_time, 4),
            max_operand_mb=round(be_si.max_operand_nbytes() / 1e6, 2),
        )
    )

    # 3. Dense baseline on the lowered form — only while the assembly
    # fits; past that the row records WHY it is absent.
    if m * n <= 1 << 25:
        low2 = slp.to_block_angular()
        low2.block_structure = None  # keep it off the scenario route
        r = _solve_timed(low2, "cpu-native", tol=1e-8)
        add(
            dict(
                base,
                engine="dense(cpu-native,lowered)",
                tol=1e-8,
                status=r.status.value,
                iters=int(r.iterations),
                time_s=round(r.solve_time, 4),
                setup_s=round(r.setup_time, 4),
                max_operand_mb=round(m * m * 8 / 1e6, 2),
            )
        )
    else:
        add(
            dict(
                base,
                engine="dense(cpu-native,lowered)",
                tol=1e-8,
                status="skipped",
                skip_reason=(
                    f"dense normal-equations assembly would be "
                    f"{m * m * 8 / 1e9:.1f} GB at m={m}"
                ),
            )
        )
    return rows


def _bench_multihost(args) -> list:
    """Multi-host harness rows (``--multihost``): the SAME storm-class
    instance solved by the sharded backend in 1-process and N-process
    `jax.distributed` worlds (distributed/launcher — each world spawned
    fresh, 2 virtual CPU devices per process on the harness, ICI/DCN on
    a pod). Wall time is the slowest rank's in-process solve wall (the
    SPMD program finishes in lockstep; process spawn/import is reported
    separately as launch overhead). CPU-harness figures measure the
    cross-process dataflow, not TPU speed — the TPU-pod measurement is
    the ROADMAP follow-on, and ``--require-tpu`` aborts before any
    fallback row here like everywhere else."""
    import tempfile

    from distributedlpsolver_tpu.distributed.launcher import run_world

    K = 8 if args.quick else 24
    spec = {
        "instance": "storm",
        "scenarios": K,
        "block_m": 24,
        "block_n": 36,
        "first_stage_n": 24,
        "seed": 1,
        "tol": 1e-8,
    }
    m = K * 24
    n = 24 + K * 36
    worlds = [1, 2] if args.quick else [1, 2, 4]
    rows = []
    base_wall = None
    for ws in worlds:
        workdir = tempfile.mkdtemp(prefix=f"dlps-bench-mh-{ws}-")
        t0 = time.perf_counter()
        res = run_world(
            "sharded_solve", spec, world_size=ws, workdir=workdir,
            local_devices=2, timeout=600,
        )
        launch_wall = time.perf_counter() - t0
        solve_wall = max(r["wall_s"] for r in res.values())
        objs = sorted(r["objective"] for r in res.values())
        statuses = {r["status"] for r in res.values()}
        if ws == 1:
            base_wall = solve_wall
        row = {
            "family": "multihost",
            "instance": f"storm K={K} ({m}x{n})",
            "m": m,
            "n": n,
            "world_size": ws,
            "global_devices": 2 * ws,
            "status": sorted(statuses)[0] if len(statuses) == 1 else "mixed",
            "iters": int(next(iter(res.values()))["iterations"]),
            "solve_wall_s": round(solve_wall, 3),
            "launch_wall_s": round(launch_wall, 3),
            "objective_spread": round(objs[-1] - objs[0], 12),
            "speedup_vs_1proc": (
                round(base_wall / solve_wall, 3) if base_wall else None
            ),
            "platform": args.platform,
        }
        rows.append(row)
        _log(json.dumps(row))
    return rows


def _bench_elastic(args) -> list:
    """Closed-loop elasticity rows (``--elastic``): a deterministic
    LoadRamp over a LIVE plane — one router over the shared registry, a
    backend pool owned by an in-process ElasticController — measuring
    what the closed loop buys and costs: sync p50/p99 before / during /
    after the ramp, the pool-size trajectory, scale-out lead times
    (signal observed -> backend serving), the brownout ladder's engaged
    window and shed count, and graceful scale-in on release. Shapes are
    calibrated so one CPU backend genuinely saturates under the peak
    (~32 rps capacity at batch 4 vs the 48 rps peak) — the overload is
    real, not simulated. CPU-harness figures measure the control loop
    and serving plane, not TPU speed; ``--require-tpu`` aborts before
    any fallback row here like everywhere else."""
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from distributedlpsolver_tpu.net.chaos import ChaosPlane, LoadRamp
    from distributedlpsolver_tpu.obs.stats import percentile
    from distributedlpsolver_tpu.serve.elastic import (
        ElasticConfig,
        ElasticController,
    )

    shape = (96, 288)
    n_ramp = 120 if args.quick else 240

    def post(url, body=None, timeout=60.0):
        req = urllib.request.Request(
            url,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"} if body else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {}
        except (urllib.error.URLError, OSError, ValueError) as e:
            return 599, {"error": f"{type(e).__name__}: {e}"}

    workdir = tempfile.mkdtemp(prefix="dlps-bench-elastic-")
    plane = ChaosPlane(workdir)
    registry_path = os.path.join(workdir, "registry.json")
    buckets_json = os.path.join(workdir, "ladder.json")
    with open(buckets_json, "w") as fh:
        fh.write(json.dumps([{"m": shape[0], "n": shape[1], "batch": 4}]))
    brownout = {
        "depth_high": 0.5, "depth_low": 0.125, "reject_rate_high": 1.0,
        "engage_after_s": 0.2, "escalate_after_s": 0.4,
        "release_after_s": 0.5, "retry_after_s": 0.05,
    }
    ctl = ElasticController(ElasticConfig(
        registry_path=registry_path,
        min_backends=1,
        max_backends=3,
        poll_s=0.2,
        load_high=6.0,
        reject_rate_high=0.5,
        out_sustain_s=0.4,
        load_low=1.0,
        in_sustain_s=2.0,
        cooldown_s=1.0,
        flap_window_s=60.0,
        flap_max_actions=24,
        workdir=workdir,
        buckets_json=buckets_json,
        backend_flags=(
            "--flush-ms", "20", "--batch", "4", "--queue-depth", "16",
            "--brownout", json.dumps(brownout, separators=(",", ":")),
            "--quiet",
        ),
        heartbeat_s=0.25,
    ))
    try:
        t0 = time.perf_counter()
        ctl.start()
        if ctl.pool_size() < 1:
            raise RuntimeError("elastic bench: min pool never came up")
        router = plane.spawn_router("bench-router", [], registry_path)
        if not plane.wait_ready(router, 60):
            raise RuntimeError("elastic bench: router never came up")
        adopt_deadline = time.perf_counter() + 30.0
        while time.perf_counter() < adopt_deadline:
            c, o = post(router.url + "/statusz", timeout=5.0)
            if c == 200 and any(
                b.get("healthy") for b in o.get("backends", [])
            ):
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("elastic bench: router never adopted pool")
        _log(
            f"elastic plane up in {time.perf_counter() - t0:.1f}s "
            f"(pool {ctl.pool_size()}, router {router.url})"
        )

        def sync_wave(n, tag, gap_fn):
            """Fire n sync solves (one thread each, gap_fn(k)-paced) and
            return per-request submit->verdict walls in ms. 429s retry
            after the structured hint — the retry wait is PART of the
            measured latency, which is the point: brownout converts
            overload into bounded extra latency, not lost work."""
            lat, lock = [], threading.Lock()

            def drive(k):
                t = time.perf_counter()
                deadline = t + 120.0
                while True:
                    c, o = post(
                        router.url + "/v1/solve",
                        {"m": shape[0], "n": shape[1], "seed": k,
                         "tenant": "bench", "id": f"{tag}-{k}"},
                    )
                    if c == 429:
                        time.sleep(min(
                            float(o.get("retry_after_s", 0.05) or 0.05), 1.0
                        ))
                    elif c in (502, 503, 599):
                        if time.perf_counter() > deadline:
                            return
                        time.sleep(0.05)
                    else:
                        break
                if c == 200 and o.get("status") == "optimal":
                    with lock:
                        lat.append((time.perf_counter() - t) * 1e3)

            ws = []
            for k in range(n):
                w = threading.Thread(target=drive, args=(k,), daemon=True)
                w.start()
                ws.append(w)
                time.sleep(gap_fn(k))
            for w in ws:
                w.join(timeout=180)
            return lat

        # Phase 1 — base: trickle load on the min pool (steady-state
        # latency floor the ramp phases are compared against).
        n_base = 8 if args.quick else 12
        lat_base = sync_wave(n_base, "base", lambda k: 0.5)

        # Phase 2 — ramp: LoadRamp to a saturating peak; a monitor
        # samples pool size and the max brownout stage across backends.
        ramp = LoadRamp(n_ramp, peak_rps=48.0, base_rps=3.0)
        done = threading.Event()
        pool_peak = [ctl.pool_size()]
        brownout_samples = []  # (t_rel_s, max stage across the pool)
        t_ramp = time.perf_counter()

        def monitor():
            while not done.is_set():
                pool_peak[0] = max(pool_peak[0], ctl.pool_size())
                stage = 0
                for m in ctl.statusz()["pool"]:
                    c, o = post(m["url"] + "/statusz", timeout=2.0)
                    if c == 200:
                        bo = (o.get("stats") or {}).get("brownout") or {}
                        stage = max(stage, int(bo.get("stage", 0) or 0))
                brownout_samples.append(
                    (time.perf_counter() - t_ramp, stage)
                )
                done.wait(0.1)

        mon = threading.Thread(target=monitor, daemon=True)
        mon.start()
        n_actions_pre = len(ctl.actions())
        lat_ramp = sync_wave(n_ramp, "ramp", ramp.gap_s)
        ramp_wall = time.perf_counter() - t_ramp
        done.set()
        mon.join(timeout=30)

        # Phase 3 — settle: wait for the drain back to min_backends,
        # then re-measure the trickle (did release restore the floor?).
        t_in = time.perf_counter()
        while time.perf_counter() - t_in < 120.0:
            if ctl.pool_size() <= ctl.config.min_backends:
                break
            time.sleep(0.3)
        scale_in_wall = time.perf_counter() - t_in
        lat_settle = sync_wave(n_base, "settle", lambda k: 0.5)

        actions = ctl.actions()[n_actions_pre:]
        outs = [a for a in actions if a["event"] == "scale_out"]
        ins = [a for a in actions if a["event"] == "scale_in"]
        engaged = [t for t, s in brownout_samples if s >= 1]
        hist = ctl.history()
        rows = []
        for phase, lat, extra in (
            ("base", lat_base, {"n": n_base, "pool": 1}),
            ("ramp", lat_ramp, {
                "n": n_ramp,
                "wall_s": round(ramp_wall, 3),
                "peak_rps": 48.0,
                "pool_peak": pool_peak[0],
                "scale_outs": len(outs),
                "scale_out_lead_ms": (
                    [round(a["ms"]) for a in outs] or None
                ),
                "brownout_stage_peak": max(
                    (s for _, s in brownout_samples), default=0
                ),
                "brownout_engaged_s": round(
                    max(engaged) - min(engaged), 3
                ) if engaged else 0.0,
            }),
            ("settle", lat_settle, {
                "n": n_base,
                "pool": ctl.pool_size(),
                "scale_ins": len(ins),
                "drained": sum(bool(a.get("drained")) for a in ins),
                "scale_in_wall_s": round(scale_in_wall, 3),
            }),
        ):
            row = {
                "family": "elastic",
                "phase": phase,
                "instance": f"dense {shape[0]}x{shape[1]} batch=4",
                "completed": len(lat),
                "latency_ms_p50": (
                    round(percentile(lat, 50), 3) if lat else None
                ),
                "latency_ms_p99": (
                    round(percentile(lat, 99), 3) if lat else None
                ),
                "platform": args.platform,
                **extra,
            }
            rows.append(row)
            _log(json.dumps(row))
        # The trajectory rides the ramp row (it IS the ramp's story);
        # sampled at the controller's own control cycle.
        rows[1]["pool_trajectory"] = [
            [round(t, 2), n] for t, n in hist
        ]
        return rows
    finally:
        ctl.shutdown(drain=False)
        plane.shutdown_all()
        shutil.rmtree(workdir, ignore_errors=True)


def _bench_tail(args) -> list:
    """Tail-tolerance rows (``--tail``): one sync wave through a LIVE
    3-backend plane with one backend SIGSTOPped mid-wave, measured with
    hedging OFF and then ON — what adaptive hedging buys on the p99
    when a straggler appears, against the same healthy-floor wave. OFF
    rows censor straggler-stuck requests at the client timeout (the
    honest rendering of "this request would have waited out the full
    forward timeout"); ON rows carry the router's hedging ledger. CPU
    harness: these rows measure the routing plane, not TPU speed;
    ``--require-tpu`` aborts before any fallback row as everywhere."""
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from distributedlpsolver_tpu.net.chaos import ChaosPlane
    from distributedlpsolver_tpu.obs.stats import percentile

    shape = (96, 288)
    n_wave = 16 if args.quick else 24
    cap_s = 15.0  # censor bound for straggler-stuck requests (OFF mode)

    def post(url, body=None, timeout=60.0):
        req = urllib.request.Request(
            url,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"} if body else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {}
        except (urllib.error.URLError, OSError, ValueError) as e:
            return 599, {"error": f"{type(e).__name__}: {e}"}

    workdir = tempfile.mkdtemp(prefix="dlps-bench-tail-")
    plane = ChaosPlane(workdir)
    buckets_json = os.path.join(workdir, "ladder.json")
    with open(buckets_json, "w") as fh:
        fh.write(json.dumps([{"m": shape[0], "n": shape[1], "batch": 4}]))
    try:
        t0 = time.perf_counter()
        names = ["tail-be-a", "tail-be-b", "tail-be-c"]
        for name in names:
            plane.spawn_backend(
                name,
                buckets_json=buckets_json,
                extra_flags=["--flush-ms", "20", "--batch", "4"],
            )
        for name in names:
            if not plane.wait_ready(plane.procs[name], 180):
                raise RuntimeError(f"tail bench: {name} never came up")
        _log(f"tail plane up in {time.perf_counter() - t0:.1f}s")
        victim = names[-1]
        rows = []
        for mode in ("off", "on"):
            rname = f"tail-router-{mode}"
            router = plane.spawn_router(
                rname,
                [plane.procs[n].url for n in names],
                os.path.join(workdir, f"registry-{mode}.json"),
                extra_flags=(
                    ["--hedge", "--hedge-rate-cap", "0.5",
                     "--retry-budget", "50", "--retry-budget-burst", "50"]
                    if mode == "on"
                    else ["--no-hedge"]
                ),
            )
            if not plane.wait_ready(router, 60):
                raise RuntimeError(f"tail bench: {rname} never came up")

            def fire(n, base, timeout_s):
                """n near-simultaneous sync solves; returns (walls_ms,
                censored_count) with client-timeout walls censored at
                the cap instead of dropped."""
                walls, censored, lock = [], [0], threading.Lock()

                def drive(k):
                    t = time.perf_counter()
                    c, o = post(
                        router.url + "/v1/solve",
                        {"m": shape[0], "n": shape[1], "seed": base + k,
                         "tenant": "bench", "id": f"tail-{mode}-{base + k}"},
                        timeout=timeout_s,
                    )
                    wall = (time.perf_counter() - t) * 1e3
                    with lock:
                        if c == 599:
                            censored[0] += 1
                            walls.append(timeout_s * 1e3)
                        else:
                            walls.append(wall)

                ws = []
                for k in range(n):
                    w = threading.Thread(target=drive, args=(k,), daemon=True)
                    w.start()
                    ws.append(w)
                    time.sleep(0.02)
                for w in ws:
                    w.join(timeout=timeout_s + 30)
                return walls, censored[0]

            # Warm until every backend's digest is warm (ON mode needs
            # >= hedge_min_samples; OFF gets the same treatment so the
            # healthy floors are comparable).
            sent = 0
            while sent < 120:
                fire(6, 1000 + sent, 90.0)
                sent += 6
                c, o = post(router.url + "/statusz", timeout=5.0)
                fwd = [
                    b.get("forwards", 0) for b in o.get("backends", [])
                ]
                if c == 200 and fwd and min(fwd) >= 10:
                    break
            healthy, _ = fire(n_wave, 2000, 90.0)
            plane.sigstop(victim)
            straggler, censored = fire(n_wave, 3000, cap_s)
            plane.sigcont(victim)
            plane.wait_ready(plane.procs[victim], 60)
            c, o = post(router.url + "/statusz", timeout=5.0)
            row = {
                "family": "tail",
                "phase": f"hedge_{mode}",
                "instance": f"dense {shape[0]}x{shape[1]} batch=4",
                "n": n_wave,
                "healthy_ms_p50": round(percentile(healthy, 50), 3),
                "healthy_ms_p99": round(percentile(healthy, 99), 3),
                "latency_ms_p50": round(percentile(straggler, 50), 3),
                "latency_ms_p99": round(percentile(straggler, 99), 3),
                "censored_at_ms": cap_s * 1e3,
                "censored": censored,
                "platform": args.platform,
            }
            if mode == "on" and c == 200:
                row["hedging"] = o.get("hedging")
            rows.append(row)
            _log(json.dumps(row))
            # This mode's router is done; the stuck OFF-mode legs died
            # with it rather than lingering into the ON measurement.
            plane.kill9(rname)
        return rows
    finally:
        plane.shutdown_all()
        shutil.rmtree(workdir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="small shapes (smoke)")
    ap.add_argument("--suite", action="store_true", help="all five reference configs")
    ap.add_argument("--full", action="store_true", help="reference-scale shapes")
    ap.add_argument("--scale", action="store_true",
                    help="pass/fail scale-regression tier -> SCALE_CHECK.json")
    ap.add_argument("--serve", action="store_true",
                    help="serving-throughput row only (rps, p50/p99, "
                    "padding waste, warm recompiles) as the stdout JSON line")
    ap.add_argument("--sparse", action="store_true",
                    help="huge-sparse tier rows (sparse-iterative vs "
                    "PDHG vs dense on one storm-profile instance; "
                    "density/nnz/cg_iters columns) -> BENCH_SPARSE.json")
    ap.add_argument("--scenario", action="store_true",
                    help="stochastic scenario tier rows (scenario-"
                    "decomposed IPM vs lowered block-angular vs sparse-"
                    "iterative on one two-stage storm instance; K + "
                    "schur/link split + peak operand bytes) -> "
                    "BENCH_SCENARIO.json")
    ap.add_argument("--multihost", action="store_true",
                    help="multi-host harness rows: the storm instance "
                    "through 1 vs N jax.distributed processes "
                    "(sharded backend, CPU harness; --require-tpu "
                    "honored) -> BENCH_MULTIHOST.json")
    ap.add_argument("--elastic", action="store_true",
                    help="closed-loop elasticity rows: sync p50/p99 "
                    "before/during/after a saturating LoadRamp over a "
                    "live router + ElasticController pool, with the "
                    "pool trajectory, scale-out lead times, and the "
                    "brownout engaged window -> BENCH_ELASTIC.json")
    ap.add_argument("--tail", action="store_true",
                    help="tail-tolerance rows: p50/p99 of a sync wave "
                    "over a live 3-backend plane with one backend "
                    "SIGSTOPped mid-wave, hedging off vs on (the "
                    "hedging ledger rides the on row) -> BENCH_TAIL.json")
    ap.add_argument("--obs", action="store_true",
                    help="tracing-overhead A/B rows: the steady-state "
                    "serve shape with the distributed-tracing layer off "
                    "vs on (per-request contexts + live Chrome tracer), "
                    "pinning p50/p99 overhead and the zero-warm-"
                    "recompile invariant -> BENCH_OBS.json")
    ap.add_argument("--serve-http", action="store_true",
                    help="serving rows incl. the HTTP network plane: the "
                    "in-process row plus a localhost POST /v1/solve row, "
                    "so network overhead is attributed (README 'Network "
                    "serving')")
    # "tpu" (the north-star backend name, BASELINE.json:5) — the dense
    # two-phase path, which measures fastest on the headline config
    # (0.72 s vs 0.90 s via the Schur backend, whose per-iteration flop
    # advantage is below the dispatch-latency floor at this size but pays
    # 7 extra iterations). Pass --backend auto for structure-aware routing.
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--baseline-backend", default="cpu-native")
    ap.add_argument("--mps", default=None, help="bench this MPS file instead")
    ap.add_argument(
        "--require-tpu", action="store_true",
        help="hard-fail (exit 4) instead of falling back to CPU when the "
        "accelerator is unavailable — a fallback round produces only "
        'unquotable "cpu-fallback" rows (BENCH_r05)',
    )
    args = ap.parse_args()
    if args.mps and not os.path.exists(args.mps):
        ap.error(f"--mps {args.mps!r}: file not found")  # before any solve

    from distributedlpsolver_tpu.utils.accel import require_tpu

    require_tpu(args.require_tpu)  # abort BEFORE the fallback path below

    import jax

    fell_back = False
    try:
        devs = jax.devices()
    except RuntimeError as e:  # accelerator claim failed — fall back to CPU
        _log(f"accelerator unavailable ({e}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        fell_back = True
    _log(f"devices: {devs}")
    # Every JSON row this run writes carries the platform it ACTUALLY ran
    # on; a fallback run stamps the distinct "cpu-fallback" so its figures
    # can never masquerade as backend=tpu measurements (VERDICT "What's
    # weak" #1 — the silent-fallback rows).
    args.platform = "cpu-fallback" if fell_back else jax.default_backend()
    if fell_back:
        _log(
            "=== CPU FALLBACK: the requested accelerator was unavailable; "
            "all figures below are host-CPU numbers and every JSON row is "
            'stamped "platform": "cpu-fallback" ==='
        )

    from distributedlpsolver_tpu.backends import available_backends

    backend = args.backend
    if backend not in available_backends():
        _log(f"backend {backend!r} unknown; using 'tpu'")
        backend = args.backend = "tpu"

    _obs_enable()

    if args.multihost:
        rows = _bench_multihost(args)
        for r in rows:
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_MULTIHOST.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"multihost rows -> {out}")
        print(json.dumps(rows[-1]))  # headline: the widest world's row
        return 0  # multihost tier is its own run; no headline solve after

    if args.elastic:
        rows = _bench_elastic(args)
        for r in rows:
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_ELASTIC.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"elastic rows -> {out}")
        print(json.dumps(rows[1]))  # headline: the ramp row
        return 0  # elasticity tier is its own run; no headline solve after

    if args.tail:
        rows = _bench_tail(args)
        for r in rows:
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_TAIL.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"tail rows -> {out}")
        print(json.dumps(rows[-1]))  # headline: the hedging-on row
        return 0  # tail tier is its own run; no headline solve after

    if args.obs:
        rows = _bench_obs(args)
        for r in rows:
            r.setdefault("platform", args.platform)
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_OBS.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"obs rows -> {out}")
        print(json.dumps(rows[-1]))  # headline: the tracing-on row
        return 0  # obs tier is its own run; no headline solve after

    if args.scenario:
        rows = _bench_scenario(args)
        for r in rows:
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_SCENARIO.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"scenario rows -> {out}")
        print(json.dumps(rows[0]))  # headline: the decomposed-IPM row
        return 0  # scenario tier is its own run; no headline solve after

    if args.sparse:
        rows = _bench_sparse(args)
        for r in rows:
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "BENCH_SPARSE.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"sparse rows -> {out}")
        print(json.dumps(rows[0]))  # headline: the matrix-free IPM row
        return 0  # sparse tier is its own run; no headline solve after

    if args.serve or args.serve_http:
        row = _bench_serve(args.quick)
        row["platform"] = args.platform
        row["metrics"] = _obs_row(args.platform)
        print(json.dumps(row))
        if args.serve_http:
            http_row = _bench_serve_http(args.quick, inproc_row=row)
            http_row["platform"] = args.platform
            print(json.dumps(http_row))
        return 0  # serve tier is its own run; no headline solve after

    if args.scale:
        rows = run_scale(args)
        for r in rows:
            r.setdefault("platform", args.platform)
            r.setdefault("metrics", _obs_row(args.platform))
        out = os.path.join(_REPO, "SCALE_CHECK.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"scale-check rows -> {out}")
        failed = [r["check"] for r in rows if not r["pass"]]
        if failed:
            _log(f"SCALE CHECK FAILED: {failed}")
            return 1
        return 0  # scale tier is its own run; no headline solve after

    if args.suite:
        rows = run_suite(args)
        for r in rows:
            r.setdefault("platform", args.platform)
        out = os.path.join(_REPO, "BENCH_SUITE.json")
        with open(out, "w") as fh:
            json.dump(rows, fh, indent=2)
        _log(f"suite rows -> {out}")

    # Headline metric (always printed last, the ONE stdout JSON line).
    problem, config_name = _headline_problem(args)
    _log(f"headline: {config_name} on backend={backend}")
    row = _bench_one(problem, backend, args.baseline_backend)
    row["metrics"] = _obs_row(args.platform)

    print(
        json.dumps(
            {
                "metric": (
                    "wall-clock to 1e-8 rel duality gap, "
                    f"{config_name}, backend={backend} "
                    f"[{row['iters']} iters, {row['iters_per_sec']:.2f} it/s, "
                    f"status={row['status']}]"
                ),
                "value": row["time_s"],
                "unit": "seconds",
                "platform": args.platform,
                "vs_baseline": row["vs_baseline"],
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
